"""Platform factory: (scaler, watcher, client) per platform.

Parity with the reference's scheduler layer
(dlrover/python/scheduler/factory.py + kubernetes.py:444LoC k8sClient
/ ray.py RayClient): one place that knows how to talk to each cluster
flavor. Platforms:

* ``local``     — in-process FakeClusterClient; used by standalone
                  mode, tests, and chaos drills.
* ``gke``       — real Kubernetes via the ``kubernetes`` package,
                  TPU pod-slices with GKE TPU selectors. The import is
                  gated: this environment has no k8s, so construction
                  raises with instructions rather than at import time.
* ``ray``       — gated the same way.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from dlrover_tpu.master.scaler import (
    ClusterClient,
    FakeClusterClient,
    PodEventWatcher,
    TPUPodScaler,
)


@dataclasses.dataclass
class Platform:
    name: str
    client: ClusterClient
    scaler: TPUPodScaler
    watcher_cls: type = PodEventWatcher

    def make_watcher(self, job_manager) -> PodEventWatcher:
        return self.watcher_cls(
            self.scaler.job_name, self.client, job_manager
        )


class GKEClusterClient(ClusterClient):
    """Real Kubernetes client for GKE TPU pod-slices. Constructed
    lazily so environments without the k8s SDK still import cleanly."""

    def __init__(self, namespace: str = "default"):
        try:
            import kubernetes  # noqa: F401
        except ImportError as exc:
            raise RuntimeError(
                "platform 'gke' needs the kubernetes package; this "
                "environment does not ship it — use platform='local' "
                "or install kubernetes in your cluster image"
            ) from exc
        from kubernetes import client as k8s_client, config

        config.load_incluster_config()
        self.namespace = namespace
        self.core = k8s_client.CoreV1Api()
        self.custom = k8s_client.CustomObjectsApi()

    def create_pod(self, spec):
        body = _pod_manifest(spec, self.namespace)
        self.core.create_namespaced_pod(self.namespace, body)

    def delete_pod(self, name):
        self.core.delete_namespaced_pod(name, self.namespace)

    def list_pods(self, job_name):
        pods = self.core.list_namespaced_pod(
            self.namespace, label_selector=f"dlrover-job={job_name}"
        )
        return [
            {
                "name": p.metadata.name,
                "job": job_name,
                "phase": p.status.phase,
                "node_id": int(
                    p.metadata.labels.get("dlrover-node-id", -1)
                ),
            }
            for p in pods.items
        ]

    def create_service(self, spec):
        from kubernetes import client as k8s_client

        svc = k8s_client.V1Service(
            metadata=k8s_client.V1ObjectMeta(name=spec["name"]),
            spec=k8s_client.V1ServiceSpec(
                selector={"dlrover-pod": spec["selector"]},
                cluster_ip="None",
            ),
        )
        self.core.create_namespaced_service(self.namespace, svc)

    def patch_custom_object(self, name, body):
        # group/version must agree with the manifest's apiVersion
        # (ELASTIC_API_VERSION — the reference operator's group).
        self.custom.patch_namespaced_custom_object(
            "elastic.iml.github.io", "v1alpha1", self.namespace,
            "scaleplans", name, body,
        )

    def watch_pods(self, job_name):
        from kubernetes import watch

        w = watch.Watch()
        for event in w.stream(
            self.core.list_namespaced_pod,
            self.namespace,
            label_selector=f"dlrover-job={job_name}",
        ):
            pod = event["object"]
            yield {
                "type": event["type"],
                "pod": {
                    "name": pod.metadata.name,
                    "job": job_name,
                    "phase": pod.status.phase,
                    "reason": (pod.status.reason or ""),
                    "node_id": int(
                        pod.metadata.labels.get("dlrover-node-id", -1)
                    ),
                },
            }


class _RayWorker:
    """Default actor body: holds the pod spec and reports health —
    the execution payload (agent process) is launched by the job
    master exactly as on k8s (ref scheduler/ray.py:40 RayWorker)."""

    def __init__(self, spec):
        self.spec = spec

    def get_spec(self):
        return self.spec

    def ping(self):
        return "ok"


class RayClusterClient(ClusterClient):
    """Ray platform (ref dlrover/python/scheduler/ray.py:51
    RayClient): pods map to named, detached Ray actors; deletes are
    ray.kill; listing walks named actors of the job's namespace.
    Import-gated like GKE — this image ships no ray."""

    def __init__(self, namespace: str = "dlrover", worker_cls=None):
        try:
            import ray
        except ImportError as exc:
            raise RuntimeError(
                "platform 'ray' needs the ray package; this "
                "environment does not ship it — use platform='local' "
                "or install ray in your cluster image"
            ) from exc
        self._ray = ray
        self.namespace = namespace
        self.worker_cls = worker_cls or _RayWorker
        if not ray.is_initialized():
            ray.init(namespace=namespace, ignore_reinit_error=True)
        import threading as _threading

        # spec cache only — the cluster's named actors are the truth
        # (they survive a master restart; _specs does not)
        self._specs: dict = {}
        self._specs_mu = _threading.Lock()

    def create_pod(self, spec):
        ray = self._ray
        options = {
            "name": spec["name"],
            "namespace": self.namespace,
            "lifetime": "detached",
            "num_cpus": float(spec.get("cpu", 1) or 1),
        }
        if spec.get("tpu_chips"):
            # Ray schedules TPU hosts via the custom "TPU" resource
            options["resources"] = {"TPU": float(spec["tpu_chips"])}
        ray.remote(self.worker_cls).options(**options).remote(spec)
        with self._specs_mu:
            self._specs[spec["name"]] = dict(spec)

    def delete_pod(self, name):
        ray = self._ray
        # drop the cache entry FIRST: an intentionally removed pod
        # must never resurface as "Failed" (the watcher would
        # relaunch it)
        with self._specs_mu:
            self._specs.pop(name, None)
        try:
            handle = ray.get_actor(name, namespace=self.namespace)
        except ValueError:
            return  # already gone
        ray.kill(handle, no_restart=True)

    def list_pods(self, job_name):
        from ray.util import list_named_actors

        alive = {
            a["name"] if isinstance(a, dict) else a
            for a in list_named_actors(all_namespaces=False)
        }
        with self._specs_mu:
            specs = {
                n: dict(s) for n, s in self._specs.items()
            }
        prefix = f"{job_name}-"
        out = []
        seen = set()
        for name, spec in specs.items():
            if spec.get("job") != job_name:
                continue
            seen.add(name)
            out.append(
                {
                    "name": name,
                    "job": job_name,
                    "phase": (
                        "Running" if name in alive else "Failed"
                    ),
                    "node_id": spec.get("node_id", -1),
                }
            )
        # Detached actors survive a master restart; a fresh client has
        # an empty cache, so cluster-side actors of this job must
        # still be listed (names are "{job}-{type}-{id}").
        for name in alive - seen:
            if not name.startswith(prefix):
                continue
            tail = name[len(prefix):]
            try:
                node_id = int(tail.rsplit("-", 1)[-1])
            except ValueError:
                node_id = -1
            out.append(
                {
                    "name": name,
                    "job": job_name,
                    "phase": "Running",
                    "node_id": node_id,
                }
            )
        return out

    def create_service(self, spec):
        # Ray named actors are directly addressable; no Service object
        return None

    def patch_custom_object(self, name, body):
        # no CRDs on Ray: scale plans execute in-process
        return None

    def watch_pods(self, job_name):
        """Poll-diff watcher: yields Deleted/Modified events the way
        the k8s watch stream does (the scaler's PodEventWatcher is
        platform-agnostic over this)."""
        import time as _time

        last: dict = {}
        while True:
            now = {
                p["name"]: p for p in self.list_pods(job_name)
            }
            for name, pod in now.items():
                prev = last.get(name)
                if prev is None:
                    yield {"type": "ADDED", "pod": pod}
                elif prev["phase"] != pod["phase"]:
                    yield {"type": "MODIFIED", "pod": pod}
            for name, pod in last.items():
                if name not in now:
                    gone = dict(pod)
                    gone["phase"] = "Deleted"
                    yield {"type": "DELETED", "pod": gone}
            last = now
            _time.sleep(2.0)


# Same API group/version as the reference operator
# (go/operator/api/v1alpha1/groupversion_info.go:29) so manifests stay
# interchangeable for users migrating from it.
ELASTIC_API_VERSION = "elastic.iml.github.io/v1alpha1"


def _quantity(v) -> str:
    """k8s resource quantity: integral floats print as integers."""
    f = float(v)
    return str(int(f)) if f.is_integer() else str(v)


def _pod_manifest(spec: dict, namespace: str) -> dict:
    """TPU pod manifest: GKE schedules TPU slices via nodeSelector on
    gke-tpu-accelerator/topology (not resource requests like GPU)."""
    node_selector = {}
    if spec.get("tpu_accelerator"):
        node_selector["cloud.google.com/gke-tpu-accelerator"] = spec[
            "tpu_accelerator"
        ]
    if spec.get("tpu_slice") is not None:
        # pin multi-slice replacements into their slice's node pool
        node_selector["dlrover-tpu/slice"] = str(spec["tpu_slice"])
    requests = {}
    if spec.get("cpu"):
        requests["cpu"] = _quantity(spec["cpu"])
    if spec.get("memory_mb"):
        requests["memory"] = f"{int(spec['memory_mb'])}Mi"
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": spec["name"],
            "namespace": namespace,
            "labels": {
                "dlrover-job": spec["job"],
                "dlrover-pod": spec["name"],
                "dlrover-node-id": str(spec.get("node_id", -1)),
            },
        },
        "spec": {
            "nodeSelector": node_selector,
            "containers": [
                {
                    "name": "worker",
                    "resources": {
                        "requests": requests,
                        "limits": {
                            "google.com/tpu": spec.get("tpu_chips", 0)
                        }
                        if spec.get("tpu_chips")
                        else {},
                    },
                }
            ],
        },
    }


def slice_inventory(
    platform_name: str = "local",
    n_slices: int = 4,
    hosts_per_slice: int = 1,
    chips_per_host: int = 4,
    accelerator: str = "tpu",
):
    """Slice inventory for a :class:`~dlrover_tpu.pool.SlicePool`.

    ``local`` synthesizes ``n_slices`` identical slices (tests,
    drills, single-host pools). Cluster platforms cannot be probed
    from this environment (no k8s/ray SDK): pass an explicit
    ``SliceSpec`` list to the pool instead, built from your node-pool
    labels (``dlrover-tpu/slice`` — the same label the scaler pins
    replacements with)."""
    from dlrover_tpu.pool.slice_pool import SliceSpec

    if platform_name != "local":
        raise RuntimeError(
            f"platform {platform_name!r} slice discovery needs the "
            "cluster SDK; build the SliceSpec inventory explicitly "
            "from your node pools and pass it to SlicePool"
        )
    return [
        SliceSpec(
            slice_id=i,
            accelerator=accelerator,
            hosts=hosts_per_slice,
            chips_per_host=chips_per_host,
        )
        for i in range(n_slices)
    ]


def elasticjob_manifest(
    job_name: str,
    namespace: str = "default",
    distribution_strategy: str = "AllreduceStrategy",
    resource_limits: Optional[dict] = None,
    replica_specs: Optional[dict] = None,
    optimize_mode: str = "single-job",
    brain_service: str = "",
    enable_elastic_scheduling: bool = True,
    enable_dynamic_sharding: bool = True,
    envs: Optional[dict] = None,
    priority: Optional[int] = None,
    tenant: str = "",
    queue: str = "",
) -> dict:
    """ElasticJob CRD manifest — field-for-field the reference's
    ElasticJobSpec (go/operator/api/v1alpha1/elasticjob_types.go:29-67:
    distributionStrategy, resourceLimits, optimizeMode, brainService,
    enableElasticScheduling, enableDynamicSharding, replicaSpecs,
    envs) plus the pool-scheduler fields (``priority`` band 0-9,
    ``tenant`` quota account, ``queue``) mapped onto
    PoolSubmitRequest by the operator (deploy/README.md)."""
    spec: dict = {
        "distributionStrategy": distribution_strategy,
        "replicaSpecs": replica_specs or {},
    }
    if priority is not None:
        spec["priority"] = int(priority)
    if tenant:
        spec["tenant"] = tenant
    if queue:
        spec["queue"] = queue
    if resource_limits:
        spec["resourceLimits"] = {
            k: str(v) for k, v in resource_limits.items()
        }
    if optimize_mode:
        spec["optimizeMode"] = optimize_mode
    if brain_service:
        spec["brainService"] = brain_service
    # always emitted: an omitted key would let a CRD/webhook default
    # silently flip an explicit False back to enabled
    spec["enableElasticScheduling"] = bool(enable_elastic_scheduling)
    spec["enableDynamicSharding"] = bool(enable_dynamic_sharding)
    if envs:
        spec["envs"] = dict(envs)
    return {
        "apiVersion": ELASTIC_API_VERSION,
        "kind": "ElasticJob",
        "metadata": {"name": job_name, "namespace": namespace},
        "spec": spec,
    }


def _pod_meta(job_name: str, node) -> dict:
    """PodMeta of the ScalePlan CRD (scaleplan_types.go:67).
    ``resource`` is a corev1.ResourceList, so TPU chips ride it as the
    extended resource ``google.com/tpu``; accelerator type and slice
    pin travel as labels (an additive field — reference-shaped
    manifests without it stay valid)."""
    res = node.config_resource
    resource = {}
    labels = {}
    if res is not None:
        if res.cpu:
            resource["cpu"] = _quantity(res.cpu)
        if res.memory_mb:
            resource["memory"] = f"{int(res.memory_mb)}Mi"
        if res.chips:
            resource["google.com/tpu"] = str(res.chips)
        if res.tpu_type:
            labels["dlrover-tpu/accelerator"] = res.tpu_type
        if res.slice_id >= 0:
            labels["dlrover-tpu/slice"] = str(res.slice_id)
    name = f"{job_name}-{node.type}-{node.id}"
    meta = {
        "name": name,
        "id": node.id,
        "type": node.type,
        "rankIndex": node.rank,
        "service": name,
        "resource": resource,
    }
    if labels:
        meta["labels"] = labels
    return meta


def scaleplan_manifest(
    name: str,
    owner_job: str,
    plan,
    namespace: str = "default",
    replica_resource_specs: Optional[dict] = None,
    ps_hosts: Optional[list] = None,
) -> dict:
    """ScalePlan CRD manifest — the reference's ScaleSpec
    (go/operator/api/v1alpha1/scaleplan_types.go:39-54:
    replicaResourceSpecs, createPods, removePods, migratePods,
    psHosts, ownerJob) built from a master ScalePlan."""
    spec: dict = {"ownerJob": owner_job}
    if replica_resource_specs:
        spec["replicaResourceSpecs"] = replica_resource_specs
    if plan.launch_nodes:
        spec["createPods"] = [
            _pod_meta(owner_job, n) for n in plan.launch_nodes
        ]
    if plan.remove_nodes:
        spec["removePods"] = [
            _pod_meta(owner_job, n) for n in plan.remove_nodes
        ]
    if ps_hosts:
        spec["psHosts"] = list(ps_hosts)
    return {
        "apiVersion": ELASTIC_API_VERSION,
        "kind": "ScalePlan",
        "metadata": {"name": name, "namespace": namespace},
        "spec": spec,
    }


def get_platform(
    name: str,
    job_name: str,
    client: Optional[ClusterClient] = None,
    **kwargs,
) -> Platform:
    if name == "local":
        client = client or FakeClusterClient()
    elif name == "gke":
        client = client or GKEClusterClient(**kwargs)
    elif name == "ray":
        client = client or RayClusterClient(**kwargs)
    else:
        raise ValueError(f"unknown platform {name!r}")
    scaler = TPUPodScaler(job_name, client)
    return Platform(name=name, client=client, scaler=scaler)
