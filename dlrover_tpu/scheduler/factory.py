"""Platform factory: (scaler, watcher, client) per platform.

Parity with the reference's scheduler layer
(dlrover/python/scheduler/factory.py + kubernetes.py:444LoC k8sClient
/ ray.py RayClient): one place that knows how to talk to each cluster
flavor. Platforms:

* ``local``     — in-process FakeClusterClient; used by standalone
                  mode, tests, and chaos drills.
* ``gke``       — real Kubernetes via the ``kubernetes`` package,
                  TPU pod-slices with GKE TPU selectors. The import is
                  gated: this environment has no k8s, so construction
                  raises with instructions rather than at import time.
* ``ray``       — gated the same way.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from dlrover_tpu.master.scaler import (
    ClusterClient,
    FakeClusterClient,
    PodEventWatcher,
    TPUPodScaler,
)


@dataclasses.dataclass
class Platform:
    name: str
    client: ClusterClient
    scaler: TPUPodScaler
    watcher_cls: type = PodEventWatcher

    def make_watcher(self, job_manager) -> PodEventWatcher:
        return self.watcher_cls(
            self.scaler.job_name, self.client, job_manager
        )


class GKEClusterClient(ClusterClient):
    """Real Kubernetes client for GKE TPU pod-slices. Constructed
    lazily so environments without the k8s SDK still import cleanly."""

    def __init__(self, namespace: str = "default"):
        try:
            import kubernetes  # noqa: F401
        except ImportError as exc:
            raise RuntimeError(
                "platform 'gke' needs the kubernetes package; this "
                "environment does not ship it — use platform='local' "
                "or install kubernetes in your cluster image"
            ) from exc
        from kubernetes import client as k8s_client, config

        config.load_incluster_config()
        self.namespace = namespace
        self.core = k8s_client.CoreV1Api()
        self.custom = k8s_client.CustomObjectsApi()

    def create_pod(self, spec):
        body = _pod_manifest(spec, self.namespace)
        self.core.create_namespaced_pod(self.namespace, body)

    def delete_pod(self, name):
        self.core.delete_namespaced_pod(name, self.namespace)

    def list_pods(self, job_name):
        pods = self.core.list_namespaced_pod(
            self.namespace, label_selector=f"dlrover-job={job_name}"
        )
        return [
            {
                "name": p.metadata.name,
                "job": job_name,
                "phase": p.status.phase,
                "node_id": int(
                    p.metadata.labels.get("dlrover-node-id", -1)
                ),
            }
            for p in pods.items
        ]

    def create_service(self, spec):
        from kubernetes import client as k8s_client

        svc = k8s_client.V1Service(
            metadata=k8s_client.V1ObjectMeta(name=spec["name"]),
            spec=k8s_client.V1ServiceSpec(
                selector={"dlrover-pod": spec["selector"]},
                cluster_ip="None",
            ),
        )
        self.core.create_namespaced_service(self.namespace, svc)

    def patch_custom_object(self, name, body):
        self.custom.patch_namespaced_custom_object(
            "dlrover.tpu.io", "v1", self.namespace, "scaleplans",
            name, body,
        )

    def watch_pods(self, job_name):
        from kubernetes import watch

        w = watch.Watch()
        for event in w.stream(
            self.core.list_namespaced_pod,
            self.namespace,
            label_selector=f"dlrover-job={job_name}",
        ):
            pod = event["object"]
            yield {
                "type": event["type"],
                "pod": {
                    "name": pod.metadata.name,
                    "job": job_name,
                    "phase": pod.status.phase,
                    "reason": (pod.status.reason or ""),
                    "node_id": int(
                        pod.metadata.labels.get("dlrover-node-id", -1)
                    ),
                },
            }


def _pod_manifest(spec: dict, namespace: str) -> dict:
    """TPU pod manifest: GKE schedules TPU slices via nodeSelector on
    gke-tpu-accelerator/topology (not resource requests like GPU)."""
    node_selector = {}
    if spec.get("tpu_accelerator"):
        node_selector["cloud.google.com/gke-tpu-accelerator"] = spec[
            "tpu_accelerator"
        ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": spec["name"],
            "namespace": namespace,
            "labels": {
                "dlrover-job": spec["job"],
                "dlrover-pod": spec["name"],
                "dlrover-node-id": str(spec.get("node_id", -1)),
            },
        },
        "spec": {
            "nodeSelector": node_selector,
            "containers": [
                {
                    "name": "worker",
                    "resources": {
                        "limits": {
                            "google.com/tpu": spec.get("tpu_chips", 0)
                        }
                        if spec.get("tpu_chips")
                        else {},
                    },
                }
            ],
        },
    }


def get_platform(
    name: str,
    job_name: str,
    client: Optional[ClusterClient] = None,
    **kwargs,
) -> Platform:
    if name == "local":
        client = client or FakeClusterClient()
    elif name == "gke":
        client = client or GKEClusterClient(**kwargs)
    elif name == "ray":
        raise RuntimeError(
            "platform 'ray' is not available in this build; the "
            "scaler seam (master/scaler.py ClusterClient) is where a "
            "Ray actor client plugs in"
        )
    else:
        raise ValueError(f"unknown platform {name!r}")
    scaler = TPUPodScaler(job_name, client)
    return Platform(name=name, client=client, scaler=scaler)
