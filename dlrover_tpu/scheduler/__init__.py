"""Platform scheduler abstraction (ref dlrover/python/scheduler/)."""

from dlrover_tpu.scheduler.factory import get_platform  # noqa: F401
