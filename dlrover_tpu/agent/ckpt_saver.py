"""Agent-side async checkpoint saver.

Parity with the reference's AsyncCheckpointSaver
(dlrover/python/elastic_agent/torch/ckpt_saver.py:369 —
start_async_saving_ckpt:415, register_signal_handler:441,
save_shm_to_storage:570, commit_checkpoint:757, TempDirCheckpointSaver
:795): a daemon in the host-agent process drains save events from the
trainer, copies shm → storage off the training critical path, flushes
shm on SIGTERM or right before an elastic restart, and commits a step
only when every rank's shard landed (temp-dir rename + done-files +
tracker file).

This process never imports jax — it must not grab the TPU chip the
trainer holds.
"""

from __future__ import annotations

import signal
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from dlrover_tpu.common.ckpt_shm import SharedMemoryHandler
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedQueue,
)
from dlrover_tpu.common.storage import CheckpointStorage, get_storage
from dlrover_tpu.trainer.flash_checkpoint.engine import (
    CKPT_EVENT_QUEUE,
    CKPT_STATUS_DICT,
    TRACKER_FILE,
    WRITING_PREFIX,
    done_dir,
    pack_shard_file,
    step_dir,
    writing_dir,
)

logger = get_logger("ckpt_saver")


class AsyncCheckpointSaver:
    """Persists trainer-staged shm checkpoints asynchronously.

    One instance per host agent. Serves the IPC primitives the trainer
    engines connect to (event queue, per-shard locks, status dict).

    ``local_shard_num``: training processes on this host.
    ``global_shard_num``: training processes job-wide (commit waits for
    this many shard files).
    ``is_commit_owner``: exactly one agent in the job (node rank 0)
    finalizes commits.
    """

    _instance: Optional["AsyncCheckpointSaver"] = None

    def __init__(
        self,
        checkpoint_dir: str,
        local_shard_num: int = 1,
        global_shard_num: Optional[int] = None,
        is_commit_owner: bool = True,
        storage: Optional[CheckpointStorage] = None,
        commit_timeout: float = 600.0,
    ):
        self.checkpoint_dir = checkpoint_dir.rstrip("/")
        self.local_shard_num = local_shard_num
        self.global_shard_num = global_shard_num or local_shard_num
        self.is_commit_owner = is_commit_owner
        self.commit_timeout = commit_timeout
        self.storage = storage or get_storage()
        self._events = SharedQueue(CKPT_EVENT_QUEUE, server=True)
        self._status = SharedDict(CKPT_STATUS_DICT, server=True)
        self._locks = [
            SharedLock(f"ckpt_{i}", server=True)
            for i in range(local_shard_num)
        ]
        self._shms = [
            SharedMemoryHandler(i) for i in range(local_shard_num)
        ]
        # A restarted agent must not re-commit steps already published
        # (the rename would collide); recover progress from the tracker.
        self._persisted_step = self._read_tracker()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._persist_lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    @classmethod
    def start_async_saving_ckpt(cls, **kwargs) -> "AsyncCheckpointSaver":
        """Singleton start, mirroring the reference classmethod.

        The constructor's checkpoint_dir is a default: save events
        carry the trainer's authoritative dir and the running saver
        adopts it, so a second start with a different dir (agent
        re-rendezvous after the trainer already saved) reuses the
        instance instead of failing."""
        if cls._instance is None:
            cls._instance = cls(**kwargs)
            cls._instance.start()
        elif kwargs.get("checkpoint_dir", "").rstrip("/") != (
                cls._instance.checkpoint_dir):
            logger.info(
                "reusing running checkpoint saver (dir %s; requested "
                "%s will apply if save events name it)",
                cls._instance.checkpoint_dir,
                kwargs.get("checkpoint_dir"),
            )
        return cls._instance

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._saving_loop, name="ckpt-saver", daemon=True
        )
        self._thread.start()

    def register_signal_handler(self) -> None:
        """Flush shm to storage on SIGTERM (preemption notice), then
        re-raise default handling so the agent still terminates."""
        orig_term = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            logger.info("SIGTERM: flushing shm checkpoint to storage")
            try:
                self.save_shm_to_storage()
            finally:
                if callable(orig_term):
                    orig_term(signum, frame)
                else:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    signal.raise_signal(signal.SIGTERM)

        signal.signal(signal.SIGTERM, handler)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for h in self._locks:
            h.close()
        self._events.close()
        self._status.close()
        for shm in self._shms:
            shm.close()
        if AsyncCheckpointSaver._instance is self:
            AsyncCheckpointSaver._instance = None

    # -- main loop -------------------------------------------------------

    def _saving_loop(self) -> None:
        import queue as _q

        while not self._stop.is_set():
            try:
                event = self._events.get(timeout=0.5)
            except _q.Empty:
                continue
            except (ConnectionError, OSError):
                return  # server shut down
            if event.get("type") == "save":
                step = int(event["step"])
                evt_dir = (event.get("dir") or "").rstrip("/")
                if evt_dir and evt_dir != self.checkpoint_dir:
                    logger.info(
                        "adopting trainer checkpoint dir %s", evt_dir
                    )
                    self.checkpoint_dir = evt_dir
                try:
                    self.save_step_checkpoint(step)
                except Exception:  # noqa: BLE001
                    logger.exception("persisting step %s failed", step)

    # -- persistence -----------------------------------------------------

    def _snapshot_shards(self):
        """Snapshot every local shard at one *consistent* step.

        The trainer stages steps monotonically; if shard k advanced
        between our reads, re-read until all shards agree (bounded
        retries) so a commit never mixes two steps' tensors."""
        for _ in range(8):
            snapshots = []
            for i in range(self.local_shard_num):
                with self._locks[i]:
                    snap = self._shms[i].load()
                if snap is None:
                    logger.warning("no shm state for local shard %s", i)
                    return None
                snapshots.append(snap)
            steps = {s[0] for s in snapshots}
            if len(steps) == 1:
                return snapshots
            logger.info(
                "shards hold mixed steps %s; re-snapshotting", steps)
            time.sleep(0.05)
        logger.error("shards never converged to one step; giving up")
        return None

    def save_step_checkpoint(self, step: int) -> bool:
        """Copy every local shard's shm to storage and commit when the
        job-wide shard set is complete. ``step`` is advisory — the shm
        contents (one consistent step across shards) win."""
        with self._persist_lock:
            snapshots = self._snapshot_shards()
            if snapshots is None:
                return False
            step = snapshots[0][0]
            # The staged metadata names the trainer's checkpoint dir —
            # authoritative even when the only save events so far were
            # memory-only (flash fast path flushed before a restart).
            staged_dir = (snapshots[0][2].get("_checkpoint_dir")
                          or "").rstrip("/")
            if staged_dir and staged_dir != self.checkpoint_dir:
                logger.info(
                    "adopting staged checkpoint dir %s", staged_dir)
                self.checkpoint_dir = staged_dir
                self._persisted_step = self._read_tracker()
            if step <= self._persisted_step:
                return True
            wdir = writing_dir(self.checkpoint_dir, step)
            ddir = done_dir(self.checkpoint_dir, step)
            with ThreadPoolExecutor(
                    max_workers=min(8, self.local_shard_num)) as pool:
                futs = [
                    pool.submit(self._persist_shard, wdir, step,
                                entries, extra, payload)
                    for _, entries, extra, payload in snapshots
                ]
                ranks = [f.result() for f in futs]
            for rank in ranks:
                self.storage.write_bytes(b"", f"{ddir}/{rank}.done")
            if self.is_commit_owner:
                committed = self.commit_checkpoint(step)
            else:
                committed = self._wait_commit(step)
            if committed:
                self._persisted_step = step
                self._status.set("latest_persisted_step", step)
            return committed

    def _persist_shard(self, wdir: str, step: int, entries, extra,
                       payload: bytes) -> int:
        rank = int(extra.get("_global_rank", 0))
        data = pack_shard_file(step, entries, extra, payload)
        self.storage.write_bytes(data, f"{wdir}/shard_{rank}.ckpt")
        return rank

    def _read_tracker(self) -> int:
        path = f"{self.checkpoint_dir}/{TRACKER_FILE}"
        try:
            if self.storage.exists(path):
                return int(self.storage.read_bytes(path).decode().strip())
        except (ValueError, OSError):
            pass
        return -1

    def commit_checkpoint(self, step: int) -> bool:
        """Wait for all ranks' done-files, then publish: rename temp
        dir → step dir, update tracker, sweep stale temp dirs. Every
        stage is idempotent so a committer crash at any point can be
        retried by the restarted agent."""
        wdir = writing_dir(self.checkpoint_dir, step)
        sdir = step_dir(self.checkpoint_dir, step)
        ddir = done_dir(self.checkpoint_dir, step)
        deadline = time.monotonic() + self.commit_timeout
        while time.monotonic() < deadline:
            if self.storage.exists(sdir):
                break  # rename already happened (this run or a prior one)
            done = [f for f in self.storage.listdir(ddir)
                    if f.endswith(".done")]
            if len(done) >= self.global_shard_num:
                self.storage.rename(wdir, sdir)
                break
            time.sleep(0.1)
        else:
            logger.error(
                "commit timeout for step %s: %s/%s shards done",
                step, len(self.storage.listdir(ddir)),
                self.global_shard_num)
            return False
        if self._read_tracker() < step:
            self.storage.write_bytes(
                str(step).encode(),
                f"{self.checkpoint_dir}/{TRACKER_FILE}")
        self.storage.rmtree(ddir)
        self._sweep_stale(step)
        logger.info("committed checkpoint step %s", step)
        return True

    def _sweep_stale(self, committed_step: int) -> None:
        """Remove writing/done dirs from failed or superseded attempts
        (≤ the committed step) so commit timeouts never leak a full
        checkpoint's worth of storage."""
        for name in self.storage.listdir(self.checkpoint_dir):
            for prefix in (WRITING_PREFIX, ".done_"):
                if not name.startswith(prefix):
                    continue
                try:
                    s = int(name[len(prefix):])
                except ValueError:
                    continue
                if s <= committed_step:
                    self.storage.rmtree(
                        f"{self.checkpoint_dir}/{name}")

    def _wait_commit(self, step: int) -> bool:
        """Non-owner agents wait for the owner's rename to land."""
        sdir = step_dir(self.checkpoint_dir, step)
        deadline = time.monotonic() + self.commit_timeout
        while time.monotonic() < deadline:
            if self.storage.exists(sdir):
                return True
            time.sleep(0.1)
        return False

    def save_shm_to_storage(self) -> bool:
        """Flush whatever step the shm currently holds — called on
        SIGTERM, on trainer failure, and before an elastic restart
        (the reference's _save_ckpt_to_storage, training.py:572)."""
        with self._locks[0]:
            snap = self._shms[0].load()
        if snap is None:
            logger.info("no shm checkpoint state to flush")
            return False
        if snap[0] <= self._persisted_step:
            logger.info("shm step %s already persisted", snap[0])
            return True
        logger.info("flushing shm checkpoint step %s to storage",
                    snap[0])
        return self.save_step_checkpoint(snap[0])

    def latest_persisted_step(self) -> int:
        return self._persisted_step
