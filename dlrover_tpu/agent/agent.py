"""Per-host elastic agent: supervises the training process.

Parity: dlrover/python/elastic_agent/torch/training.py (
MasterRendezvousHandler :132, ElasticTrainingAgent :313, launch_agent
:642), redesigned for the JAX process model: ONE training process per
host owns all local TPU chips (instead of torchelastic's
one-process-per-GPU), and world bootstrap hands the process
``jax.distributed.initialize`` coordinates (coordinator addr, process
id, process count) via env vars instead of a c10d TCPStore.

Restart semantics are the reference's: on membership change or process
failure the agent kills and respawns the *training process* while the
agent itself stays up, which is exactly the teardown/re-init JAX needs
since its distributed world is static per initialization.
"""

from __future__ import annotations

import collections
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.comm import find_free_port
from dlrover_tpu.common.config import ensure_framework_on_pythonpath
from dlrover_tpu.common.constants import (
    EventAction,
    NodeAction,
    NodeEnv,
    NodeType,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger

logger = get_logger("agent")

_HEARTBEAT_FAILURES = obs.counter(
    "dlrover_agent_heartbeat_failures_total",
    "Agent->master heartbeat RPC failures (consecutive streaks are "
    "logged once per power-of-two length, not per tick)",
)


class RendezvousTimeoutError(RuntimeError):
    pass


class MasterRendezvousHandler:
    """Agent-side rendezvous: join, poll for the frozen world, compute
    this node's rank and the JAX bootstrap coordinates."""

    def __init__(
        self,
        client: MasterClient,
        local_world_size: int,
        rdzv_name: str = RendezvousName.TRAINING,
        timeout: float = 600.0,
        poll_interval: float = 0.3,
    ):
        self.client = client
        self.local_world_size = local_world_size
        self.rdzv_name = rdzv_name
        self.timeout = timeout
        self.poll_interval = poll_interval

    def next_rendezvous(self) -> "WorldSpec":
        round_ = self.client.join_rendezvous(
            self.local_world_size, rdzv_name=self.rdzv_name
        )
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            rdzv_round, group, world = self.client.get_comm_world(
                rdzv_name=self.rdzv_name
            )
            if world and self.client.node_rank in world:
                return self._build_spec(rdzv_round, group, world)
            if world and self.client.node_rank not in world:
                # Frozen without us (e.g. node_unit rounding): rejoin.
                round_ = self.client.join_rendezvous(
                    self.local_world_size, rdzv_name=self.rdzv_name
                )
            time.sleep(self.poll_interval)
        raise RendezvousTimeoutError(
            f"{self.rdzv_name} rendezvous not completed in {self.timeout}s "
            f"(joined round {round_})"
        )

    def _build_spec(
        self, rdzv_round: int, group: int, world: Dict[int, int]
    ) -> "WorldSpec":
        ranks = sorted(world.keys())
        my_rank = ranks.index(self.client.node_rank)
        # Process ids: one training process per node; process_id equals
        # the node's position; chips-per-host is the local world size.
        spec = WorldSpec(
            round=rdzv_round,
            group=group,
            world=world,
            node_world_size=len(ranks),
            node_rank=my_rank,
            process_id=my_rank,
            num_processes=len(ranks),
        )
        # Rank-0 of the world publishes the coordinator endpoint.
        kv_key = f"coordinator/{self.rdzv_name}/{rdzv_round}/{group}"
        if my_rank == 0:
            host = os.getenv("DLROVER_TPU_HOST_IP", "127.0.0.1")
            port = find_free_port()
            spec.coordinator = f"{host}:{port}"
            self.client.kv_set(kv_key, spec.coordinator.encode())
        else:
            spec.coordinator = self.client.kv_wait(
                kv_key, timeout=self.timeout
            ).decode()
        return spec


@dataclass
class WorldSpec:
    round: int
    group: int
    world: Dict[int, int]
    node_world_size: int
    node_rank: int
    process_id: int
    num_processes: int
    coordinator: str = ""


@dataclass
class AgentConfig:
    node_id: int = 0
    node_rank: int = -1
    # Role this agent's node plays (NodeType): "worker" nodes join the
    # elastic rendezvous; an "evaluator" runs its command standalone
    # (it follows checkpoints, not the training world) while the
    # master still owns its lifecycle (critical role, relaunch).
    node_type: str = "worker"
    local_world_size: int = 1
    max_restarts: int = 3
    monitor_interval: float = 2.0
    rdzv_timeout: float = 600.0
    network_check: bool = False
    # With network_check: a node the master judges a straggler (>2x
    # median check time) exits instead of joining training, so the
    # scaler replaces it (ref dlrover-run --exclude-straggler,
    # trainer/torch/elastic_run.py:99-137).
    exclude_straggler: bool = False
    heartbeat_interval: float = 15.0
    # >0 enables hang detection: restart the training process when no
    # step progress for this many seconds (ref: atorch
    # --relaunch_on_hanging, fault_tolerance/custom_agent.py:19).
    hang_timeout: float = 0.0
    env: Dict[str, str] = field(default_factory=dict)


class ElasticAgent:
    """Supervises one training process through restarts and membership
    changes."""

    def __init__(
        self,
        config: AgentConfig,
        entry_cmd: List[str],
        client: Optional[MasterClient] = None,
    ):
        self.config = config
        self.entry_cmd = entry_cmd
        self.client = client or MasterClient.singleton()
        self._rdzv = MasterRendezvousHandler(
            self.client,
            config.local_world_size,
            timeout=config.rdzv_timeout,
        )
        self._proc: Optional[subprocess.Popen] = None
        # Tail of the child's stderr, kept so failure reports carry the
        # actual error text (OOM / RESOURCE_EXHAUSTED / preemption) the
        # master's classifier keys on (ref: error log monitor).
        self._stderr_tail: Deque[bytes] = collections.deque(maxlen=50)
        self._stderr_thread: Optional[threading.Thread] = None
        self._tail_lock = threading.Lock()
        self._restart_count = 0
        self._stop = threading.Event()
        self._spec: Optional[WorldSpec] = None
        self._ckpt_saver = None
        # Set by the heartbeat thread; acted on ONLY by the monitor
        # loop so process lifecycle has a single owner (no concurrent
        # kill/spawn races).
        self._restart_requested = threading.Event()
        # Set by the master's `cordon` heartbeat action (remediation):
        # the agent parks its trainer and sits out rendezvous while
        # still heartbeating; RESTART_TRAINING un-cordons.
        self._cordon_requested = threading.Event()
        # In-flight PROFILE capture worker (one at a time).
        self._profile_thread: Optional[threading.Thread] = None

    # -- process management -------------------------------------------------

    def _spawn(self, spec: WorldSpec) -> None:
        # Remove the previous incarnation's step-metrics file: the
        # hang detector and training monitor must not baseline on a
        # stale step (a resume can legitimately restart at a LOWER
        # step, which a stale high-water mark would misread as a hang
        # / silence).
        from dlrover_tpu.agent.monitor import (
            default_metrics_file,
            METRICS_FILE_ENV,
        )

        try:
            os.remove(os.getenv(METRICS_FILE_ENV, default_metrics_file()))
        except OSError:
            pass
        env = ensure_framework_on_pythonpath(dict(os.environ))
        env.update(self.config.env)
        env.update(
            {
                "DLROVER_TPU_AGENT_PRESENT": "1",
                NodeEnv.NODE_ID: str(self.config.node_id),
                NodeEnv.NODE_RANK: str(spec.node_rank),
                NodeEnv.NODE_NUM: str(spec.node_world_size),
                NodeEnv.LOCAL_WORLD_SIZE: str(
                    self.config.local_world_size
                ),
                NodeEnv.COORDINATOR_ADDR: spec.coordinator,
                NodeEnv.PROCESS_ID: str(spec.process_id),
                NodeEnv.NUM_PROCESSES: str(spec.num_processes),
                NodeEnv.RESTART_COUNT: str(self._restart_count),
                NodeEnv.MASTER_ADDR: self.client._client.addr,
            }
        )
        logger.info(
            "spawning training process (round=%d rank=%d/%d restart=%d): %s",
            spec.round,
            spec.node_rank,
            spec.node_world_size,
            self._restart_count,
            " ".join(self.entry_cmd),
        )
        # Fresh deque per incarnation: if a previous pump thread out-
        # lives its 3s join (a grandchild kept the pipe open), it keeps
        # appending to the *old* deque and cannot pollute this
        # incarnation's tail or race its readers.
        with self._tail_lock:
            self._stderr_tail = collections.deque(maxlen=50)
        self._proc = subprocess.Popen(
            self.entry_cmd, env=env, stderr=subprocess.PIPE
        )
        self._stderr_thread = threading.Thread(
            target=self._pump_stderr,
            args=(self._proc.stderr, self._stderr_tail),
            daemon=True,
        )
        self._stderr_thread.start()

    def _pump_stderr(self, pipe, tail: Deque[bytes]) -> None:
        """Forward the child's stderr while keeping the last lines.

        ``tail`` is this incarnation's deque, bound at spawn time."""
        try:
            for line in iter(pipe.readline, b""):
                with self._tail_lock:
                    tail.append(line)
                try:
                    sys.stderr.buffer.write(line)
                    sys.stderr.buffer.flush()
                except (AttributeError, ValueError, OSError):
                    # stderr replaced by a text-only capture (pytest) or
                    # closed: keep the tail, drop the passthrough.
                    pass
        finally:
            pipe.close()

    def _stderr_text(self, limit: int = 2048) -> str:
        with self._tail_lock:
            lines = list(self._stderr_tail)
        text = b"".join(lines).decode("utf-8", "replace")
        return text[-limit:]

    def _kill_proc(self, grace: float = 10.0) -> None:
        if self._proc is None or self._proc.poll() is not None:
            self._join_stderr_pump()
            return
        self._proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                self._join_stderr_pump()
                return
            time.sleep(0.2)
        self._proc.kill()
        self._proc.wait()
        self._join_stderr_pump()

    def _join_stderr_pump(self) -> None:
        """Drain the old incarnation's pump thread so its buffered
        stderr cannot leak into the next incarnation's tail."""
        if (
            self._stderr_thread is not None
            and self._stderr_thread is not threading.current_thread()
        ):
            self._stderr_thread.join(timeout=3.0)
        self._stderr_thread = None

    # -- forensics ----------------------------------------------------------

    def _snapshot_trainer_stacks(self, timeout: float = 3.0) -> str:
        """The training process's Python stacks, as text.

        Alive process: SIGUSR1 triggers its flight recorder's
        C-level faulthandler dump (registered at install; works even
        with the main thread wedged in a C call) and the growth of
        its stacks file is returned. Dead process: the tail the crash
        handlers already left behind."""
        from dlrover_tpu.obs import flight_recorder as fr

        proc = self._proc
        if proc is None:
            return ""
        path = fr.stacks_file_path(proc.pid)
        try:
            before = os.path.getsize(path)
        except OSError:
            before = 0
        if proc.poll() is not None:
            return fr.read_stacks_tail(
                path, since=max(before - 8192, 0)
            )
        if not hasattr(signal, "SIGUSR1"):
            return ""
        if not fr.sigusr1_ready(proc.pid):
            # No registered handler (recorder disabled, still
            # importing, or registration failed): the default
            # disposition would KILL the process we are trying to
            # diagnose. No signal, no stacks.
            return ""
        try:
            proc.send_signal(signal.SIGUSR1)
        except OSError:
            return ""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if os.path.getsize(path) > before:
                    # Give the C handler a beat to finish the dump.
                    time.sleep(0.2)
                    break
            except OSError:
                pass
            time.sleep(0.1)
        return fr.read_stacks_tail(path, since=before)

    def _collect_forensics(self, kind: str, **notes):
        """(digest, bundle_path): snapshot the training process's
        stacks, write this agent's black-box bundle (with the trainer
        stacks embedded), and build the size-capped digest failure
        reports and the master's history carry. Never raises."""
        from dlrover_tpu.obs import flight_recorder as fr

        stacks = ""
        try:
            stacks = self._snapshot_trainer_stacks()
        except Exception:  # noqa: BLE001 — forensics must never
            # break the recovery path it documents
            logger.warning(
                "trainer stack snapshot failed", exc_info=True
            )
        rec = fr.get_flight_recorder()
        bundle_path = ""
        if rec is not None:
            # Incident facts ride THIS bundle only — merging them
            # into the recorder's persistent notes would make every
            # later diagnose/crash digest replay a stale hang.
            bundle_path = (
                rec.dump(
                    kind,
                    reason=f"agent {kind} forensics",
                    extra={"trainer_stacks": stacks},
                    incident=notes,
                )
                or ""
            )
        digest = fr.make_digest(
            kind, stacks_text=stacks, recorder=rec, incident=notes
        )
        if bundle_path:
            digest = f"bundle: {bundle_path}\n{digest}"
        return digest, bundle_path

    def _run_diagnose(self) -> None:
        """Master-pushed `diagnose` action: on-demand stack-and-state
        snapshot, shipped back as a DiagnosticsReport."""
        digest, bundle_path = self._collect_forensics("diagnose")
        self.client.report_diagnostics(
            "diagnose", bundle_path=bundle_path, digest=digest
        )

    def _on_stale_beacon(self, stamp: dict) -> None:
        """ResourceMonitor found the trainer's progress beacon wedged
        (no stamp for DLROVER_TPU_BEACON_STALL_S): capture forensics
        while the wedge is live — the SIGUSR1 stack snapshot shows
        exactly which collective the trainer is parked in — and ship
        them as a kind-``stall`` DiagnosticsReport. The master-side
        correlator does the cross-host localization; this capture is
        the host-local half of the evidence."""
        digest, bundle_path = self._collect_forensics(
            "stall",
            beacon_step=stamp.get("step"),
            beacon_microbatch=stamp.get("microbatch"),
            beacon_phase=stamp.get("phase"),
            beacon_age_s=stamp.get("age_s"),
        )
        self.client.report_diagnostics(
            "stall", bundle_path=bundle_path, digest=digest
        )

    def _run_profile(self) -> None:
        """Master-pushed `profile` action: ask the co-hosted trainer
        for an N-step step-phase/MFU capture and ship the digest back
        as a DiagnosticsReport(kind="profile").

        Runs in its own daemon thread: the capture spans N training
        steps (seconds to minutes), and the heartbeat loop must keep
        beating while the trainer gets there. One capture at a time —
        a second PROFILE while one is in flight is dropped (the
        running capture's digest answers it)."""
        if (
            self._profile_thread is not None
            and self._profile_thread.is_alive()
        ):
            logger.info("profile capture already in flight; skipping")
            return
        self._profile_thread = threading.Thread(
            target=self._profile_worker,
            name="profile-capture",
            daemon=True,
        )
        self._profile_thread.start()

    def _profile_worker(self) -> None:
        try:
            self._profile_worker_inner()
        except Exception:  # noqa: BLE001 — a failed capture must
            # neither kill the agent nor masquerade as a crash (an
            # uncaught thread exception would write a forensics
            # bundle via threading.excepthook)
            logger.warning("profile capture failed", exc_info=True)

    def _profile_worker_inner(self) -> None:
        import json as _json

        from dlrover_tpu.obs import profiling

        req_id = profiling.write_profile_request()
        wait_s = float(os.getenv("DLROVER_TPU_PROFILE_WAIT_S", "120"))
        deadline = time.monotonic() + wait_s
        digest = None
        while time.monotonic() < deadline:
            digest = profiling.read_profile_digest(expect_id=req_id)
            if digest is not None:
                break
            time.sleep(0.25)
        if digest is None:
            # The answer is itself diagnostic: no digest within the
            # wait usually means no live trainer loop (hung, between
            # restarts, or a loop without a step-phase profiler).
            self.client.report_diagnostics(
                "profile",
                digest=_json.dumps(
                    {
                        "id": req_id,
                        "error": f"no profile digest within {wait_s:.0f}s"
                        " (trainer not stepping, or its loop has no"
                        " StepPhaseProfiler)",
                    }
                ),
            )
            return
        self.client.report_diagnostics(
            "profile",
            bundle_path=profiling.profile_digest_file(),
            digest=_json.dumps(digest, indent=1, sort_keys=True),
        )

    # -- health check -------------------------------------------------------

    def run_network_check(self) -> bool:
        """Run the psum/matmul benchmark payload in a throwaway process
        group and report the result (ref: NetworkCheckElasticAgent)."""
        handler = MasterRendezvousHandler(
            self.client,
            self.config.local_world_size,
            rdzv_name=RendezvousName.NETWORK_CHECK,
            timeout=self.config.rdzv_timeout,
        )
        for _ in range(2):  # two grouping rounds localize the fault
            spec = handler.next_rendezvous()
            start = time.monotonic()
            result = subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "dlrover_tpu.trainer.network_check",
                ],
                env={
                    **ensure_framework_on_pythonpath(dict(os.environ)),
                    NodeEnv.COORDINATOR_ADDR: spec.coordinator,
                    NodeEnv.PROCESS_ID: str(spec.process_id),
                    NodeEnv.NUM_PROCESSES: str(spec.num_processes),
                },
                timeout=300,
                check=False,
            )
            elapsed = time.monotonic() - start
            normal = result.returncode == 0
            self.client.report_network_check(normal, elapsed)
        return self.network_check_verdict()

    def network_check_verdict(self) -> bool:
        """Consume the master's fault + straggler verdicts for this
        node after check results were reported. Split from
        run_network_check so the decision (incl. --exclude-straggler)
        is testable without live rendezvous timing."""
        deadline = time.monotonic() + self.config.rdzv_timeout
        faults, reason = self.client.query_fault_nodes()
        while reason == "waiting":
            if time.monotonic() > deadline:
                logger.error(
                    "network-check verdict not available within %ss "
                    "(peers never reported); treating as failure",
                    self.config.rdzv_timeout,
                )
                return False
            time.sleep(1.0)
            faults, reason = self.client.query_fault_nodes()
        if self.client.node_rank in faults:
            logger.error("this node FAILED the network check")
            return False
        try:
            stragglers, _ = self.client.query_stragglers()
        except Exception:  # noqa: BLE001 — a transient RPC failure
            # must not kill a healthy node over an advisory check
            logger.warning(
                "straggler query failed; assuming not a straggler",
                exc_info=True,
            )
            stragglers = []
        if self.client.node_rank in stragglers:
            if self.config.exclude_straggler:
                logger.error(
                    "this node is a STRAGGLER (>2x median check "
                    "time) and --exclude-straggler is set; exiting "
                    "so it gets replaced"
                )
                return False
            logger.warning(
                "this node is a STRAGGLER (>2x median check time); "
                "continuing (pass --exclude-straggler to exit "
                "instead)"
            )
        return True

    # -- main loop ----------------------------------------------------------

    def run(self) -> int:
        self.client.register_node(node_type=self.config.node_type)
        # The network check is a training-world rendezvous sized to the
        # worker fleet — an evaluator joining it would freeze a wrong-
        # sized world and skew the straggler median, so only workers
        # run it.
        is_evaluator = self.config.node_type == NodeType.EVALUATOR
        if (
            not is_evaluator
            and self.config.network_check
            and not self.run_network_check()
        ):
            self.client.report_failure(
                "network check failed",
                TrainingExceptionLevel.NODE_ERROR,
            )
            return 1
        heartbeat = threading.Thread(
            target=self._heartbeat_loop, daemon=True
        )
        heartbeat.start()
        # Telemetry to the master: node resources + training progress
        # (ref elastic_agent/monitor/{resource,training}.py).
        from dlrover_tpu.agent.monitor import (
            ResourceMonitor,
            TrainingMonitor,
        )
        from dlrover_tpu.agent.paral_config_tuner import ParalConfigTuner

        res_mon = ResourceMonitor(
            self.client, on_stale_beacon=self._on_stale_beacon
        )
        train_mon = TrainingMonitor(self.client)
        tuner = ParalConfigTuner(self.client)
        # After a master reconnect (possibly to a warm-restarted
        # replacement), resend a full telemetry snapshot immediately:
        # the new master's fleet view re-primes now, not a reporting
        # cadence later. (Registration itself is already resent by
        # the client's supervisor.)
        self.client.add_reconnect_callback(res_mon.report_once)
        res_mon.start()
        train_mon.start()
        tuner.start()
        try:
            result = self._invoke_run()
        finally:
            res_mon.stop()
            train_mon.stop()
            tuner.stop()
            self._stop.set()
        return result

    def _ensure_ckpt_saver(self, spec: WorldSpec) -> None:
        """Start/refresh the agent-hosted flash-checkpoint saver (ref:
        saver started at _invoke_run, elastic_agent/torch/
        training.py:509; agent ownership means a crashed trainer's shm
        still gets flushed). World facts refresh on every rendezvous."""
        import os as _os

        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

        default_dir = _os.path.join(
            "/tmp",
            f"dlrover_tpu_ckpt_{_os.getenv('DLROVER_TPU_JOB_NAME', 'job')}",
        )
        saver = AsyncCheckpointSaver.start_async_saving_ckpt(
            checkpoint_dir=default_dir,
            local_shard_num=1,
            global_shard_num=max(spec.num_processes, 1),
            is_commit_owner=spec.node_rank == 0,
        )
        saver.global_shard_num = max(spec.num_processes, 1)
        saver.is_commit_owner = spec.node_rank == 0
        if self._ckpt_saver is None:
            saver.register_signal_handler()
        self._ckpt_saver = saver

    def _flush_ckpt_shm(self) -> None:
        """Persist any staged-but-unpersisted checkpoint before a
        restart (ref: _save_ckpt_to_storage, training.py:572)."""
        if self._ckpt_saver is not None:
            try:
                self._ckpt_saver.save_shm_to_storage()
            except Exception:  # noqa: BLE001
                logger.warning(
                    "pre-restart checkpoint flush failed", exc_info=True
                )

    def _standalone_spec(self) -> WorldSpec:
        """World of one for roles outside the training rendezvous
        (evaluator): the process runs alone, keyed by this node."""
        return WorldSpec(
            round=0,
            group=0,
            world={self.config.node_id: self.config.local_world_size},
            node_world_size=1,
            node_rank=0,
            process_id=0,
            num_processes=self.config.local_world_size,
        )

    def _invoke_run(self) -> int:
        from dlrover_tpu.agent.hang_detector import HangDetector

        hang = (
            HangDetector(hang_timeout=self.config.hang_timeout)
            if self.config.hang_timeout > 0
            else None
        )
        if self.config.node_type == NodeType.EVALUATOR:
            # Evaluators run outside the training world: no rendezvous
            # join (which would block or distort the worker world), a
            # world of one; master-side lifecycle still applies.
            self._spec = self._standalone_spec()
        else:
            self._spec = self._rdzv.next_rendezvous()
        self._ensure_ckpt_saver(self._spec)
        self._spawn(self._spec)
        while not self._stop.is_set():
            time.sleep(self.config.monitor_interval)
            if self._cordon_requested.is_set():
                # Cordoned by the master's remediation engine: stop
                # the trainer (it would otherwise wedge the fleet's
                # collectives), skip rendezvous/membership handling so
                # this node sits OUT of the next world, keep
                # heartbeating so the master can un-cordon (rollback)
                # or retire us. A pending restart request stays set —
                # it fires the moment the cordon clears.
                if self._proc is not None and self._proc.poll() is None:
                    logger.warning(
                        "cordoned by master; stopping training "
                        "process and sitting out rendezvous"
                    )
                    obs.event(
                        "agent.cordoned", node_id=self.config.node_id
                    )
                    self._flush_ckpt_shm()
                    self._kill_proc()
                self._proc = None
                if hang is not None:
                    hang.reset()
                continue
            if hang is not None and hang.check():
                exhausted = (
                    self._restart_count >= self.config.max_restarts
                )
                logger.error(
                    "training process hung (%.0fs without step "
                    "progress); %s",
                    hang.seconds_since_progress(),
                    "giving up" if exhausted else "restarting it",
                )
                # Forensics BEFORE any kill/restart: the SIGUSR1 stack
                # snapshot needs the hung process still alive, and the
                # digest must ride the failure report so the hang is
                # diagnosable, not just counted.
                digest, bundle_path = self._collect_forensics(
                    "hang",
                    hang_seconds=round(
                        hang.seconds_since_progress(), 1
                    ),
                    last_step=hang.last_step,
                )
                action = NodeAction.RESTART_IN_PLACE
                try:
                    action = self.client.report_failure(
                        "training process hanging",
                        TrainingExceptionLevel.PROCESS_ERROR,
                        restart_count=self._restart_count,
                        fatal=exhausted,
                        diagnostics=digest,
                    )
                except Exception:  # noqa: BLE001
                    logger.warning("could not report hang", exc_info=True)
                self.client.report_diagnostics(
                    "hang", bundle_path=bundle_path, digest=digest
                )
                if exhausted:
                    self._kill_proc()  # a hung proc still holds chips
                    return 1
                if action != NodeAction.RESTART_IN_PLACE:
                    # Master took ownership (node relaunch/stop): same
                    # handover as _handle_failure.
                    logger.info(
                        "master verdict %r on hang; agent stops "
                        "supervising", action,
                    )
                    self._kill_proc()
                    return 1
                self._restart_count += 1
                self._restart_workers(reason="hang")
                hang.reset()
                continue
            code = self._proc.poll() if self._proc else None
            if code is not None:
                if code == 0:
                    logger.info("training process finished successfully")
                    try:
                        self.client.report_succeeded()
                    except Exception:  # noqa: BLE001
                        logger.warning(
                            "could not report success to master",
                            exc_info=True,
                        )
                    return 0
                if not self._handle_failure(code):
                    return code
                continue
            if self._restart_requested.is_set():
                self._restart_requested.clear()
                logger.info("master requested restart")
                self._restart_workers(reason="master_request")
            elif self._membership_changed():
                logger.info(
                    "membership changed; restarting training process "
                    "for re-rendezvous"
                )
                self._restart_workers()
        self._kill_proc()
        return 0

    def _handle_failure(self, exitcode: int) -> bool:
        """Report and decide restart. True = keep running."""
        self._join_stderr_pump()
        exhausted = self._restart_count >= self.config.max_restarts
        error_data = (
            f"training process exit code {exitcode}\n"
            + self._stderr_text()
        )
        # The dead trainer's crash hooks (excepthook bundle /
        # faulthandler stacks) already wrote to the forensics dir;
        # fold their tail + this agent's black box into a digest. It
        # rides the failure report's `diagnostics` field, NOT
        # error_data: stack frames must not perturb the master's
        # stderr keyword classifier (a frame through
        # preemption_drill.py is not a preemption).
        digest, bundle_path = self._collect_forensics(
            "crash", exit_code=exitcode
        )
        action = NodeAction.RESTART_IN_PLACE
        try:
            action = self.client.report_failure(
                error_data,
                TrainingExceptionLevel.PROCESS_ERROR,
                restart_count=self._restart_count,
                fatal=exhausted,
                diagnostics=digest,
            )
        except Exception:  # noqa: BLE001
            # An unreachable master must not take the agent down with
            # it — restarts are still locally meaningful.
            logger.warning(
                "could not report failure to master", exc_info=True
            )
        self.client.report_diagnostics(
            "crash", bundle_path=bundle_path, digest=digest
        )
        if exhausted:
            logger.error(
                "exhausted %d restarts; giving up", self.config.max_restarts
            )
            return False
        if action != NodeAction.RESTART_IN_PLACE:
            # The master took ownership (node relaunch or stop): this
            # agent must not also restart the process in place.
            logger.info(
                "master verdict %r; agent stops supervising", action
            )
            return False
        self._restart_count += 1
        self._restart_workers(reason="process_exit")
        return True

    def _restart_workers(self, reason: str = "membership") -> None:
        from dlrover_tpu import obs

        obs.event(
            "agent.worker_restart",
            reason=reason,
            restart_count=self._restart_count,
            node_id=self.config.node_id,
        )
        self._flush_ckpt_shm()
        self._kill_proc()
        self._spec = (
            self._standalone_spec()
            if self.config.node_type == NodeType.EVALUATOR
            else self._rdzv.next_rendezvous()
        )
        self._ensure_ckpt_saver(self._spec)
        self._spawn(self._spec)

    def _membership_changed(self) -> bool:
        # Evaluators are not part of the training world: worker churn
        # must not restart the evaluation loop.
        if self.config.node_type == NodeType.EVALUATOR:
            return False
        return self.client.num_nodes_waiting() > 0

    def _heartbeat_loop(self) -> None:
        streak = 0
        next_warn = 1
        while not self._stop.wait(self.config.heartbeat_interval):
            try:
                action = self.client.heartbeat()
            except Exception:  # noqa: BLE001
                # Repeated failures are counted, and warned once per
                # power-of-two streak length — a master outage must
                # show up in telemetry without a log line per tick.
                streak += 1
                _HEARTBEAT_FAILURES.inc()
                if streak >= next_warn:
                    logger.warning(
                        "heartbeat failed (%d consecutive "
                        "failure%s; next warning at %d)",
                        streak,
                        "" if streak == 1 else "s",
                        next_warn * 2,
                        exc_info=True,
                    )
                    next_warn *= 2
                continue
            if streak:
                logger.info(
                    "heartbeat recovered after %d failure%s",
                    streak, "" if streak == 1 else "s",
                )
                streak = 0
                next_warn = 1
                # The master may be a warm-restarted replacement (or
                # a cold one that lost the node table): re-announce
                # this node and let subscribers resend snapshots.
                try:
                    self.client.notify_master_recovered()
                except Exception:  # noqa: BLE001
                    logger.warning(
                        "post-recovery re-registration failed",
                        exc_info=True,
                    )
            if action == EventAction.RESTART_TRAINING.value:
                if self._cordon_requested.is_set():
                    # restart_training doubles as un-cordon (the
                    # remediation rollback path): clear the cordon
                    # FIRST so the supervision loop acts on the
                    # restart instead of skipping it.
                    self._cordon_requested.clear()
                    logger.info(
                        "master un-cordoned this node; rejoining at "
                        "the next rendezvous"
                    )
                self._restart_requested.set()
            elif action == EventAction.CORDON.value:
                logger.warning(
                    "master cordoned this node (remediation); parking "
                    "the trainer"
                )
                self._cordon_requested.set()
            elif action == EventAction.STOP_TRAINING.value:
                self._stop.set()
            elif action == EventAction.DIAGNOSE.value:
                try:
                    self._run_diagnose()
                except Exception:  # noqa: BLE001 — an on-demand
                    # snapshot must never take the heartbeat down
                    logger.warning("diagnose failed", exc_info=True)
            elif action == EventAction.PROFILE.value:
                try:
                    self._run_profile()
                except Exception:  # noqa: BLE001 — an on-demand
                    # capture must never take the heartbeat down
                    logger.warning("profile failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        self._kill_proc()
