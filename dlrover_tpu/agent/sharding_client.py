"""Worker-side dynamic sharding clients.

Parity: dlrover/python/elastic_agent/sharding/client.py:31,233
(ShardingClient / IndexShardingClient). The index client prefetches
sample indices from master-assigned shards on a background thread so
``fetch_sample_index()`` is cheap inside the input pipeline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common import messages as msg

logger = get_logger("sharding_client")


class ShardingClient:
    """Fetches whole shards; reports completion."""

    def __init__(
        self,
        dataset_name: str,
        client: Optional[MasterClient] = None,
    ):
        self.dataset_name = dataset_name
        self._client = client or MasterClient.singleton()
        self._pending: Dict[int, msg.Task] = {}
        self._lock = threading.Lock()

    def create_dataset(
        self,
        dataset_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        num_stream_partitions: int = 1,
    ) -> None:
        self._client.create_dataset(
            dataset_name=self.dataset_name,
            dataset_size=dataset_size,
            batch_size=batch_size,
            num_epochs=num_epochs,
            shuffle=shuffle,
            num_minibatches_per_shard=num_minibatches_per_shard,
            storage_type=storage_type,
            num_stream_partitions=num_stream_partitions,
        )

    def stream_barrier(self, epoch: int, step: int):
        """Commit a stream barrier for this dataset (caller quiesces
        its sparse applies first)."""
        return self._client.stream_barrier(
            self.dataset_name, epoch=epoch, step=step
        )

    def query_stream_barrier(self):
        return self._client.query_stream_barrier(self.dataset_name)

    def get_task(
        self,
        wait: bool = True,
        timeout: float = 300.0,
        return_wait: bool = False,
    ):
        """Returns the next Task or None when the dataset is
        exhausted. ``return_wait=True`` hands back the WAIT task
        itself instead of blocking/None, so callers holding
        deliverables (deferred-completion producers) can flush before
        the master's wait-for-doing-shards would deadlock them."""
        deadline = time.monotonic() + timeout
        while True:
            task = self._client.get_task(self.dataset_name)
            if task.task_type == TaskType.WAIT:
                if return_wait:
                    return task
                if not wait or time.monotonic() > deadline:
                    return None
                time.sleep(1.0)
                continue
            if task.task_type == TaskType.NONE or task.task_id < 0:
                return None
            with self._lock:
                self._pending[task.task_id] = task
            return task

    def report_task_done(self, task_id: int, success: bool = True) -> None:
        with self._lock:
            self._pending.pop(task_id, None)
        self._client.report_task_result(
            self.dataset_name, task_id, success
        )

    def checkpoint(self) -> str:
        return self._client.get_shard_checkpoint(self.dataset_name)

    def restore(self, content: str) -> None:
        self._client.restore_shard_checkpoint(self.dataset_name, content)


class IndexShardingClient(ShardingClient):
    """Streams individual sample indices out of master-assigned shards.

    The dataset's ``__getitem__`` asks for the next index; shard
    boundaries and completion reporting stay invisible to user code.
    """

    def __init__(
        self,
        dataset_name: str,
        batch_size: int,
        client: Optional[MasterClient] = None,
        defer_completion: bool = False,
    ):
        """``defer_completion=True`` changes WHEN a fully-consumed
        shard is reported done: not at the moment its last index is
        popped (the producer may still die with the materialized batch
        in hand — silently lost, since the master would never
        re-dispatch a "done" shard), but at the next explicit
        :meth:`confirm_delivered` call, which producers place right
        after the downstream hand-off (shm ring put / remote push
        ack). That makes shard completion mean "delivered", the
        at-least-once contract the chaos drills check."""
        super().__init__(dataset_name, client)
        self.batch_size = batch_size
        self.defer_completion = defer_completion
        self._indices: Deque[int] = deque()
        self._index_lock = threading.Lock()
        # task_id -> remaining sample count; completion reported at 0
        self._task_remaining: Dict[int, int] = {}
        self._current_task_queue: Deque[int] = deque()
        self._consumed_unconfirmed: List[int] = []
        self._exhausted = False

    #: fetch_sample_index(block=False) sentinel: the master answered
    #: WAIT (doing shards may still be re-queued) — the caller should
    #: flush/confirm anything it holds and retry.
    WOULD_WAIT = object()

    def fetch_sample_index(self, block: bool = True):
        """Next sample index; None when the dataset is exhausted.

        ``block=False`` returns :data:`WOULD_WAIT` instead of blocking
        when the master says WAIT. Deferred-completion producers MUST
        use this: with ``defer_completion=True`` the un-confirmed
        shard they still hold is exactly what the master is waiting
        on, so blocking here would deadlock until the shard timeout
        re-queues it — and then a stale confirm would mark the
        re-dispatched copy done with its tail batch undelivered."""
        with self._index_lock:
            if self._indices:
                self._account_consumed()
                return self._indices.popleft()
        if self._exhausted:
            return None
        if not self._prefetch(block=block):
            return self.WOULD_WAIT
        with self._index_lock:
            if not self._indices:
                return None
            self._account_consumed()
            return self._indices.popleft()

    def _account_consumed(self) -> None:
        # Called with _index_lock held, BEFORE popping one index.
        while self._current_task_queue:
            tid = self._current_task_queue[0]
            if self._task_remaining.get(tid, 0) > 0:
                self._task_remaining[tid] -= 1
                if self._task_remaining[tid] == 0:
                    self._current_task_queue.popleft()
                    done_tid = tid
                    if self.defer_completion:
                        self._consumed_unconfirmed.append(done_tid)
                        return
                    # Report outside the lock via a thread to keep the
                    # input pipeline non-blocking.
                    threading.Thread(
                        target=self.report_task_done,
                        args=(done_tid,),
                        daemon=True,
                    ).start()
                return
            self._current_task_queue.popleft()

    def confirm_delivered(self) -> int:
        """Report done every fully-consumed shard whose indices were
        all popped BEFORE this call (defer_completion mode). Producers
        call it right after a successful downstream hand-off; batches
        are built in pop order, so the hand-off covers everything
        popped so far. Returns the number of shards reported.

        The reports ride a daemon thread like the non-defer path —
        the delivery-ordering requirement is already satisfied the
        moment the tids leave the unconfirmed list, so the producer's
        batch loop need not stall on master round-trips."""
        with self._index_lock:
            ready, self._consumed_unconfirmed = (
                self._consumed_unconfirmed, []
            )
        for tid in ready:
            threading.Thread(
                target=self.report_task_done, args=(tid,), daemon=True
            ).start()
        return len(ready)

    def _prefetch(self, block: bool = True) -> bool:
        """Pull one shard into the local queue. Returns False when
        ``block=False`` and the master answered WAIT."""
        task = self.get_task(wait=block, return_wait=not block)
        if task is not None and task.task_type == TaskType.WAIT:
            return False
        if task is None:
            self._exhausted = True
            return True
        shard = task.shard
        if shard.record_indices:
            indices: List[int] = list(shard.record_indices)
        else:
            indices = list(range(shard.start, shard.end))
        with self._index_lock:
            self._indices.extend(indices)
            self._task_remaining[task.task_id] = len(indices)
            self._current_task_queue.append(task.task_id)
        return True

    def reset(self) -> None:
        with self._index_lock:
            self._indices.clear()
            self._task_remaining.clear()
            self._current_task_queue.clear()
            # Unconfirmed completions must NOT survive a reset: the
            # master re-queues those shards, and confirming a stale
            # tid afterwards would mark the re-queued shard done with
            # its batches undelivered.
            self._consumed_unconfirmed.clear()
            self._exhausted = False
