"""Parallel-config tuner (agent side).

Parity with the reference's ParalConfigTuner
(dlrover/python/elastic_agent/config/paral_config_tuner.py:31): the
master's auto-tuner publishes a ParallelConfig; the agent polls it and
drops it as a JSON file the training process reads on (re)start —
micro batch size, grad-accum, remat policy, mesh shape. The file-drop
mechanism survives training-process restarts, which is exactly when a
new config takes effect.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("paral_tuner")

CONFIG_FILE_ENV = "DLROVER_TPU_PARAL_CONFIG_FILE"


def default_config_file() -> str:
    """Job-scoped path: a leftover file from another job on the same
    host must not leak its tuning into this one."""
    job = os.getenv("DLROVER_TPU_JOB_NAME", "default")
    return f"/tmp/dlrover_tpu_paral_config_{job}.json"


class ParalConfigTuner:
    def __init__(
        self,
        client,
        config_file: Optional[str] = None,
        interval: float = 30.0,
    ):
        self.client = client
        self.config_file = config_file or os.getenv(
            CONFIG_FILE_ENV, default_config_file()
        )
        self.interval = interval
        self._seen_version = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> bool:
        """Fetch the master's config; write the file when it changed.
        Returns True if a new version landed."""
        try:
            cfg = self.client.get_parallel_config()
        except Exception:  # noqa: BLE001
            logger.debug("paral config fetch failed", exc_info=True)
            return False
        if cfg is None or cfg.version <= self._seen_version:
            return False
        self._seen_version = cfg.version
        tmp = self.config_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dataclasses.asdict(cfg), f)
        os.replace(tmp, self.config_file)
        logger.info(
            "parallel config v%d staged to %s",
            cfg.version,
            self.config_file,
        )
        return True

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="paral-tuner", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.poll_once()


def read_parallel_config(path: Optional[str] = None) -> Optional[dict]:
    """Training-process side: the staged config, or None."""
    path = path or os.getenv(CONFIG_FILE_ENV, default_config_file())
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
