"""Typed client to the job master, used by agents and trainers.

Parity: dlrover/python/elastic_agent/master_client.py:49 (MasterClient
with the retry decorator at :26), re-typed onto the msgpack schema —
plus a connection supervisor so a master outage (pod reschedule, OOM,
network partition) is ridden out instead of killing the fleet.

Two retry layers with distinct jobs:

* :class:`ConnectionSupervisor` — *transient* transport failures
  (master unreachable) are retried with exponential backoff and
  decorrelated jitter under a total outage budget
  (``DLROVER_TPU_MASTER_RECONNECT_SECONDS``, default 300 s). On the
  first success after an outage it re-registers this node (the master
  may be a warm-restarted replacement) and fires reconnect callbacks.
  Budget exhaustion raises :class:`MasterOutageError`.
* :func:`retry` — brief *application-level* hiccups (a server handler
  momentarily failing) get a couple of jittered retries. It never
  re-retries a :class:`MasterOutageError`: the supervisor already
  spent the whole outage budget.
"""

from __future__ import annotations

import functools
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import grpc

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient, RpcError
from dlrover_tpu.common.constants import (
    NodeAction,
    NodeEnv,
    RendezvousName,
)
from dlrover_tpu.common.log import get_logger

logger = get_logger("master_client")

RECONNECT_SECONDS_ENV = "DLROVER_TPU_MASTER_RECONNECT_SECONDS"
RECONNECT_BASE_ENV = "DLROVER_TPU_MASTER_RECONNECT_BASE"

# gRPC status codes that mean "the master may be down / unreachable"
# rather than "this request is wrong". Everything else is fatal for
# the call (retrying an INVALID_ARGUMENT forever helps nobody).
_TRANSIENT_GRPC_CODES = frozenset(
    (
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
        grpc.StatusCode.CANCELLED,
        grpc.StatusCode.UNKNOWN,
    )
)


class MasterOutageError(RuntimeError):
    """The master stayed unreachable past the reconnect budget."""


def is_transient_rpc_error(exc: BaseException) -> bool:
    """Transport-level failures worth riding out: a dead/restarting
    master, a partition, or an injected chaos fault. Server-side
    handler failures (our :class:`RpcError`) are NOT transient — the
    master answered, retrying blind would loop on a real bug."""
    if isinstance(exc, MasterOutageError):
        return False
    if isinstance(exc, RpcError):
        return False
    if isinstance(exc, grpc.RpcError):
        code = exc.code() if callable(getattr(exc, "code", None)) else None
        return code in _TRANSIENT_GRPC_CODES
    # ChaosDropError subclasses ConnectionError on purpose.
    return isinstance(exc, (ConnectionError, ConnectionResetError, OSError))


class ConnectionSupervisor:
    """Retries transient failures under one shared outage budget.

    Thread-safe: all of a process's RPC paths (heartbeat thread,
    resource monitor, sharding client, rendezvous poll) share the
    outage clock, which starts at the first observed failure and
    clears on any success. ``on_reconnect`` callbacks fire exactly
    once per outage, from the thread whose call first succeeded.
    """

    def __init__(
        self,
        outage_budget: Optional[float] = None,
        backoff_base: Optional[float] = None,
        backoff_cap: float = 15.0,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if outage_budget is None:
            outage_budget = float(
                os.getenv(RECONNECT_SECONDS_ENV, "") or 300.0
            )
        if backoff_base is None:
            backoff_base = float(
                os.getenv(RECONNECT_BASE_ENV, "") or 0.5
            )
        self.outage_budget = outage_budget
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._rng = rng or random.Random()
        self._sleep = sleep
        self._lock = threading.Lock()
        self._outage_since: Optional[float] = None  # monotonic
        self.on_reconnect: List[Callable[[], None]] = []
        self.outages = 0
        self.reconnects = 0

    def outage_elapsed(self) -> Optional[float]:
        with self._lock:
            if self._outage_since is None:
                return None
            return time.monotonic() - self._outage_since

    def _note_failure(self) -> float:
        """Record a transient failure; returns seconds into the
        outage."""
        now = time.monotonic()
        with self._lock:
            if self._outage_since is None:
                self._outage_since = now
                self.outages += 1
            return now - self._outage_since

    def _note_success(self) -> bool:
        """Clear any outage; True when this call ended one."""
        with self._lock:
            was_out = self._outage_since is not None
            self._outage_since = None
            if was_out:
                self.reconnects += 1
            return was_out

    def call(
        self,
        fn: Callable[[], object],
        what: str = "rpc",
        max_wait: Optional[float] = None,
    ):
        """Run ``fn``, riding out transient failures.

        ``max_wait`` caps how long THIS call may retry, independent of
        the shared outage budget — for callers that have something
        better to do locally than wait out a whole outage (e.g. a
        failure report whose caller will restart the dead trainer
        anyway)."""
        sleep_s = self.backoff_base
        warned = 1.0
        started = time.monotonic()
        while True:
            try:
                result = fn()
            except Exception as e:  # noqa: BLE001 — classified below
                if not is_transient_rpc_error(e):
                    raise
                elapsed = self._note_failure()
                waited = time.monotonic() - started
                if elapsed >= self.outage_budget or (
                    max_wait is not None and waited >= max_wait
                ):
                    raise MasterOutageError(
                        f"master unreachable for {elapsed:.1f}s "
                        f"(budget {self.outage_budget:.0f}s, call "
                        f"waited {waited:.1f}s"
                        + (
                            f" of max {max_wait:.0f}s"
                            if max_wait is not None else ""
                        )
                        + f") during {what}: {e}"
                    ) from e
                # Decorrelated jitter (never fleet-synchronized
                # thundering herd), capped and clipped to the budget.
                sleep_s = min(
                    self.backoff_cap,
                    self._rng.uniform(self.backoff_base, sleep_s * 3),
                )
                sleep_s = min(
                    sleep_s, max(self.outage_budget - elapsed, 0.05)
                )
                if max_wait is not None:
                    sleep_s = min(
                        sleep_s, max(max_wait - waited, 0.05)
                    )
                if elapsed >= warned:
                    logger.warning(
                        "master unreachable %.1fs into outage "
                        "(budget %.0fs) during %s; retrying in %.2fs",
                        elapsed, self.outage_budget, what, sleep_s,
                    )
                    warned = max(warned * 2, elapsed + sleep_s)
                self._sleep(sleep_s)
                continue
            if self._note_success():
                logger.info(
                    "master connection recovered (during %s)", what
                )
                for cb in list(self.on_reconnect):
                    try:
                        cb()
                    except Exception:  # noqa: BLE001 — a broken
                        # callback must not fail the recovered call
                        logger.warning(
                            "reconnect callback failed", exc_info=True
                        )
            return result


def retry(times: int = 3, interval: float = 1.0):
    """Brief application-level retries with jitter. Does not sleep
    after the final failed attempt (the old version wasted up to
    ``times * interval`` seconds on the error path before raising),
    and never re-retries an exhausted reconnect budget."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            last_exc: Optional[Exception] = None
            for attempt in range(times):
                try:
                    return fn(*args, **kwargs)
                except MasterOutageError:
                    # The supervisor already spent the whole outage
                    # budget; times x that again helps nobody.
                    raise
                except Exception as e:  # noqa: BLE001
                    last_exc = e
                    logger.warning(
                        "%s failed (attempt %d/%d): %s",
                        fn.__name__,
                        attempt + 1,
                        times,
                        e,
                    )
                    if attempt + 1 < times:
                        time.sleep(
                            interval
                            * (attempt + 1)
                            * random.uniform(0.5, 1.5)
                        )
            raise last_exc  # type: ignore[misc]

        return wrapped

    return decorator


class MasterClient:
    """One instance per process; safe to share across threads."""

    _singleton: Optional["MasterClient"] = None

    def __init__(
        self,
        addr: str,
        node_id: int = 0,
        node_rank: int = -1,
        job_id: str = "",
    ):
        # wait_for_ready: during a master outage the channel sits in
        # TRANSIENT_FAILURE; queued-until-connected calls recover the
        # instant the replacement master serves, instead of failing
        # fast until gRPC's backoff deigns to redial.
        # ``job_id`` (or DLROVER_TPU_POOL_JOB_ID via singleton())
        # rides every request's envelope so a multi-job pool master
        # routes this process's RPCs to ITS job's servicer; ""
        # preserves single-job behavior exactly.
        self.job_id = job_id
        self._client = RpcClient(addr, wait_for_ready=True, job_id=job_id)
        self.node_id = node_id
        self.node_rank = node_rank if node_rank >= 0 else node_id
        # Rides out master outages (reschedule, partition) on every
        # critical RPC path. Best-effort telemetry deliberately stays
        # OFF the supervisor: a trainer's step report must drop fast
        # during an outage, not block a hot loop for minutes.
        self.supervisor = ConnectionSupervisor()
        self.supervisor.on_reconnect.append(self._on_reconnected)
        # Remembered registration facts for idempotent re-register
        # after a reconnect (the master may be a warm-restarted
        # replacement that needs this node announced again; the
        # job-manager register path is re-register-safe).
        self._registration: Optional[
            Tuple[str, str, Dict[str, str]]
        ] = None
        # User hooks fired after re-registration on every reconnect
        # (e.g. resend a sharding snapshot / metrics snapshot).
        self._reconnect_callbacks: List[Callable[[], None]] = []

    # -- reconnect handling --------------------------------------------------

    def add_reconnect_callback(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` after each reconnect (post re-registration)."""
        self._reconnect_callbacks.append(fn)

    def _on_reconnected(self) -> None:
        """First successful RPC after an outage: re-announce this node
        (idempotent on the master), then let subscribers resend their
        snapshots. Uses the RAW client — the supervisor is mid-call,
        and a failure here will be healed by the next outage cycle."""
        if self._registration is not None:
            node_type, node_ip, reg_labels = self._registration
            try:
                self._client.report(
                    msg.NodeAddressRequest(
                        node_id=self.node_id,
                        node_type=node_type,
                        node_ip=node_ip,
                        labels=dict(reg_labels),
                    )
                )
                logger.info(
                    "re-registered node %d (%s) after reconnect",
                    self.node_id, node_type,
                )
            except Exception:  # noqa: BLE001
                logger.warning(
                    "post-reconnect re-registration failed",
                    exc_info=True,
                )
        for cb in list(self._reconnect_callbacks):
            try:
                cb()
            except Exception:  # noqa: BLE001
                logger.warning(
                    "reconnect callback failed", exc_info=True
                )

    def _get(
        self,
        request,
        what: Optional[str] = None,
        max_wait: Optional[float] = None,
    ):
        return self.supervisor.call(
            lambda: self._client.get(request),
            what=what or type(request).__name__,
            max_wait=max_wait,
        )

    def _report(self, request, what: Optional[str] = None):
        return self.supervisor.call(
            lambda: self._client.report(request),
            what=what or type(request).__name__,
        )

    @classmethod
    def singleton(cls) -> "MasterClient":
        if cls._singleton is None:
            addr = os.getenv(NodeEnv.MASTER_ADDR, "")
            if not addr:
                raise RuntimeError(
                    f"{NodeEnv.MASTER_ADDR} not set; is this process "
                    "running under dlrover-tpu-run?"
                )
            node_id = int(os.getenv(NodeEnv.NODE_ID, "0"))
            node_rank = int(os.getenv(NodeEnv.NODE_RANK, "-1"))
            job_id = os.getenv(NodeEnv.POOL_JOB_ID, "")
            cls._singleton = cls(
                addr, node_id, node_rank, job_id=job_id
            )
        return cls._singleton

    @classmethod
    def reset(cls) -> None:
        cls._singleton = None

    # -- node lifecycle -----------------------------------------------------

    @retry()
    def register_node(
        self,
        node_type: str = "worker",
        node_ip: str = "",
        labels: Optional[Dict[str, str]] = None,
    ):
        # Remember the facts FIRST: even if this attempt dies mid-
        # outage, the supervisor's reconnect path can re-announce.
        self._registration = (node_type, node_ip, dict(labels or {}))
        self._report(
            msg.NodeAddressRequest(
                node_id=self.node_id,
                node_type=node_type,
                node_ip=node_ip,
                labels=dict(labels or {}),
            )
        )

    @retry()
    def report_failure(
        self,
        error_data: str,
        level: str,
        restart_count: int = 0,
        fatal: bool = False,
        diagnostics: str = "",
    ) -> str:
        # Bounded wait (not the full outage budget): the caller has a
        # DEAD or HUNG trainer in hand and will restart it locally on
        # failure — blocking the supervision loop for minutes to ask
        # a dead master's opinion would hold chips hostage.
        resp = self.supervisor.call(
            lambda: self._client.report(
                msg.NodeFailureReport(
                    node_id=self.node_id,
                    error_data=error_data,
                    level=level,
                    restart_count=restart_count,
                    fatal=fatal,
                    diagnostics=diagnostics,
                )
            ),
            what="NodeFailureReport",
            max_wait=30.0,
        )
        return resp.action if resp else NodeAction.RESTART_IN_PLACE

    @retry()
    def report_succeeded(self):
        # Bounded: worth waiting a bit (an unreported success decays
        # into a heartbeat-timeout "failure" on the master), but not
        # worth pinning a finished agent to the outage budget.
        self.supervisor.call(
            lambda: self._client.report(
                msg.NodeSucceededReport(node_id=self.node_id)
            ),
            what="NodeSucceededReport",
            max_wait=60.0,
        )

    def heartbeat(self) -> str:
        """One beat. Deliberately NOT supervised: the heartbeat loop
        owns per-tick failure accounting (its failure counter and
        escalating warnings are how a master outage shows up in
        telemetry — the supervisor retrying internally would flatline
        them for any outage shorter than the whole budget) and must
        stay responsive to stop/action delivery. The bounded
        queue-until-ready timeout still heals the gRPC channel the
        moment a replacement master serves, and the loop calls
        :meth:`notify_master_recovered` on the first healthy beat
        after a failure streak."""
        resp = self._client.report(
            msg.HeartbeatRequest(
                node_id=self.node_id, timestamp=time.time()
            ),
            timeout=10.0,
        )
        return resp.action if resp else "none"

    def notify_master_recovered(self) -> None:
        """Re-register + fire resend hooks after an outage observed
        OUTSIDE the supervisor (the heartbeat loop's streak
        recovery). Idempotent — harmless if a supervised call already
        reconnected."""
        self._on_reconnected()

    # -- rendezvous ---------------------------------------------------------

    @retry()
    def join_rendezvous(
        self,
        local_world_size: int,
        rdzv_name: str = RendezvousName.TRAINING,
    ) -> int:
        resp = self._get(
            msg.JoinRendezvousRequest(
                node_id=self.node_id,
                node_rank=self.node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
            )
        )
        return resp.round

    def get_comm_world(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> Tuple[int, int, Dict[int, int]]:
        resp = self._get(
            msg.CommWorldRequest(
                node_id=self.node_id,
                node_rank=self.node_rank,
                rdzv_name=rdzv_name,
            )
        )
        return resp.round, resp.group, resp.world

    def num_nodes_waiting(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> int:
        try:
            resp = self._client.get(
                msg.WaitingNodeNumRequest(
                    node_id=self.node_id, rdzv_name=rdzv_name
                ),
                wait_for_ready=False,
            )
            return resp.waiting_num
        except Exception:  # noqa: BLE001 - polling must not kill the agent
            return 0

    @retry()
    def report_network_check(self, normal: bool, elapsed_time: float):
        self._report(
            msg.NetworkCheckResultRequest(
                node_id=self.node_rank,
                normal=normal,
                elapsed_time=elapsed_time,
            )
        )

    def query_fault_nodes(self) -> Tuple[List[int], str]:
        resp = self._get(msg.NetworkCheckQueryRequest(kind="fault"))
        return resp.nodes, resp.reason

    def query_stragglers(self) -> Tuple[List[int], str]:
        resp = self._get(
            msg.NetworkCheckQueryRequest(kind="straggler")
        )
        return resp.nodes, resp.reason

    # -- kv store -----------------------------------------------------------

    @retry()
    def kv_set(self, key: str, value: bytes):
        self._report(msg.KVStoreSetRequest(key=key, value=value))

    def kv_get(self, key: str) -> Optional[bytes]:
        resp = self._get(msg.KVStoreGetRequest(key=key))
        return resp.value if resp.found else None

    def kv_add(self, key: str, amount: int) -> int:
        # NOT supervised: the add is not idempotent — a retry after a
        # lost response would double-apply the increment (callers use
        # this for unique-id assignment). Single attempt, caller owns
        # the ambiguity of a failure, exactly as before the
        # supervisor existed.
        resp = self._client.get(
            msg.KVStoreAddRequest(key=key, amount=amount)
        )
        return resp.value

    def kv_wait(self, key: str, timeout: float = 120.0) -> bytes:
        # Monotonic deadline: an NTP step must not fire or mask it.
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            value = self.kv_get(key)
            if value is not None:
                return value
            time.sleep(0.2)
        raise TimeoutError(f"kv key {key!r} not set within {timeout}s")

    # -- data sharding ------------------------------------------------------

    @retry()
    def create_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        task_type: str = "training",
        num_stream_partitions: int = 1,
    ):
        self._report(
            msg.DatasetShardParams(
                batch_size=batch_size,
                num_epochs=num_epochs,
                dataset_size=dataset_size,
                shuffle=shuffle,
                num_minibatches_per_shard=num_minibatches_per_shard,
                dataset_name=dataset_name,
                task_type=task_type,
                storage_type=storage_type,
                num_stream_partitions=num_stream_partitions,
            )
        )

    @retry()
    def stream_barrier(
        self, dataset_name: str, epoch: int, step: int
    ) -> msg.StreamBarrierResponse:
        """Commit a stream barrier: coordinated PS flush stamped with
        the shard ledger's HWM, then a durable journal record. The
        caller must have quiesced its sparse applies first."""
        return self._get(msg.StreamBarrierRequest(
            dataset_name=dataset_name, epoch=epoch, step=step
        ))

    @retry()
    def query_stream_barrier(
        self, dataset_name: str
    ) -> msg.StreamBarrierResponse:
        return self._get(msg.StreamBarrierQueryRequest(
            dataset_name=dataset_name
        ))

    def get_task(self, dataset_name: str) -> msg.Task:
        return self._get(
            msg.TaskRequest(node_id=self.node_id, dataset_name=dataset_name)
        )

    @retry()
    def report_task_result(
        self, dataset_name: str, task_id: int, success: bool = True
    ):
        self._report(
            msg.TaskResultRequest(
                node_id=self.node_id,
                dataset_name=dataset_name,
                task_id=task_id,
                success=success,
            )
        )

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._get(
            msg.ShardCheckpointRequest(dataset_name=dataset_name)
        )
        return resp.content

    @retry()
    def restore_shard_checkpoint(self, dataset_name: str, content: str):
        self._report(
            msg.RestoreShardRequest(dataset_name=dataset_name, content=content)
        )

    # -- auto-tuning --------------------------------------------------------

    def get_parallel_config(self):
        """Master-pushed tuning config (ref ParalConfigTuner)."""
        return self._get(
            msg.ParallelConfigRequest(node_id=self.node_id)
        )

    # -- metrics ------------------------------------------------------------

    def report_step(self, step: int, tokens: int = 0):
        try:
            self._client.report(
                msg.StepReport(
                    node_id=self.node_id,
                    timestamp=time.time(),
                    step=step,
                    tokens=tokens,
                ),
                wait_for_ready=False,
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def report_resource(
        self,
        cpu_percent: float,
        memory_mb: int,
        hbm_used_gb: float = 0.0,
        duty_cycle: float = 0.0,
    ):
        try:
            self._client.report(
                msg.ResourceStats(
                    node_id=self.node_id,
                    cpu_percent=cpu_percent,
                    memory_mb=memory_mb,
                    hbm_used_gb=hbm_used_gb,
                    duty_cycle=duty_cycle,
                ),
                wait_for_ready=False,
            )
        except Exception:  # noqa: BLE001
            pass

    def report_metrics_snapshot(
        self,
        host: str,
        registry: Optional[dict] = None,
        resource: Optional[dict] = None,
        step_times: Optional[list] = None,
        events: Optional[list] = None,
        timestamp: Optional[float] = None,
        beacon: Optional[dict] = None,
    ):
        """Ship this host's telemetry snapshot to the master's
        FleetAggregator (ResourceMonitor cadence). Best-effort like
        every other telemetry report."""
        try:
            self._client.report(
                msg.MetricsSnapshotReport(
                    node_id=self.node_id,
                    host=host,
                    timestamp=(
                        timestamp if timestamp is not None else time.time()
                    ),
                    registry=registry or {},
                    resource=resource or {},
                    step_times=list(step_times or []),
                    events=list(events or []),
                    beacon=dict(beacon or {}),
                ),
                wait_for_ready=False,
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    # -- forensics ----------------------------------------------------------

    def report_diagnostics(
        self, kind: str, bundle_path: str = "", digest: str = ""
    ):
        """Ship a forensics digest (hang / crash / on-demand diagnose)
        to the master's per-node history. Best-effort: forensics must
        never block or fail the recovery path it documents."""
        try:
            self._client.report(
                msg.DiagnosticsReport(
                    node_id=self.node_id,
                    kind=kind,
                    bundle_path=bundle_path,
                    digest=digest,
                    timestamp=time.time(),
                ),
                wait_for_ready=False,
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            logger.warning(
                "could not ship %s diagnostics to master", kind,
                exc_info=True,
            )

    def query_diagnostics(self, node_id: int = -1) -> List:
        """The master's stored DiagnosticsReport history (tools)."""
        resp = self._get(
            msg.DiagnosticsQueryRequest(node_id=node_id)
        )
        return list(resp.reports)

    def query_health(
        self,
        node_id: int = -1,
        include_history: bool = False,
        max_wait: Optional[float] = None,
    ) -> msg.HealthQueryResponse:
        """The master's health plane: composite score + active
        verdicts (optionally the transition history), filtered to one
        node with ``node_id``. Tools and the operator use this as the
        typed counterpart of the /healthz endpoint; probes pass
        ``max_wait`` so a down master fails fast instead of riding
        out the full reconnect budget."""
        return self._get(
            msg.HealthQueryRequest(
                node_id=node_id, include_history=include_history
            ),
            max_wait=max_wait,
        )

    def query_remediation(
        self,
        node_id: int = -1,
        limit: int = 0,
        max_wait: Optional[float] = None,
    ) -> msg.RemediationQueryResponse:
        """The master's remediation engine: mode (enabled/dry-run),
        cordoned nodes, decision history with per-governor audit
        trails, and whether a probation window is currently failing.
        Probes pass ``max_wait`` so a down master fails fast."""
        return self._get(
            msg.RemediationQueryRequest(node_id=node_id, limit=limit),
            max_wait=max_wait,
        )

    def request_profile(self, node_id: int) -> None:
        """Operator trigger: ask the master to queue a PROFILE action
        for ``node_id`` (its agent captures an N-step phase/MFU
        digest into the diagnostics history)."""
        self._report(msg.ProfileActionRequest(node_id=node_id))

    # -- serving plane ----------------------------------------------------

    def serve_submit(
        self,
        prompt: List[int],
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        request_id: str = "",
    ) -> msg.ServeSubmitResponse:
        """Submit one generation request to the master's router.
        ``request_id`` is an idempotence token: RPC retries resubmit
        the same id and the ledger keeps one entry. When the caller
        supplies none, a client-side UUID is minted BEFORE the call —
        a supervisor retry after a lost response must replay the same
        token, or every network blip would double-queue the
        request."""
        import uuid

        return self._get(
            msg.ServeSubmitRequest(
                prompt=[int(t) for t in prompt],
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                request_id=request_id or f"req-{uuid.uuid4().hex}",
            )
        )

    def serve_result(
        self, request_id: str, max_wait: Optional[float] = None
    ) -> msg.ServeResultResponse:
        return self._get(
            msg.ServeResultRequest(request_id=request_id),
            max_wait=max_wait,
        )

    def serve_pull(
        self, replica_id: int, max_items: int = 1
    ) -> List[msg.ServeWorkItem]:
        """Replica side: pull up to ``max_items`` dispatched
        requests off the router's queue."""
        resp = self._get(
            msg.ServePullRequest(
                replica_id=replica_id, max_items=max_items
            )
        )
        return list(resp.items)

    def serve_complete(
        self,
        replica_id: int,
        request_id: str,
        tokens: List[int],
        ttft_s: float = 0.0,
        tpot_s: float = 0.0,
        finish_reason: str = "",
        error: str = "",
        phases: Optional[Dict[str, float]] = None,
        handoff: Optional[dict] = None,
    ) -> None:
        """``handoff`` (a packed HandoffPayload wire dict) turns the
        report into a prefill->decode stage transition: the KV rides
        this same RPC seam up to the master's staging queue."""
        self._report(
            msg.ServeCompletedReport(
                replica_id=replica_id,
                request_id=request_id,
                tokens=[int(t) for t in tokens],
                ttft_s=ttft_s,
                tpot_s=tpot_s,
                finish_reason=finish_reason,
                error=error,
                phases={
                    str(k): float(v)
                    for k, v in (phases or {}).items()
                },
                handoff=dict(handoff or {}),
            )
        )

    def serve_stats(self, replica_id: int, stats: dict) -> None:
        """Best-effort replica telemetry; a lost report is the next
        interval's problem, never the step loop's."""
        try:
            self._report(
                msg.ServeStatsReport(
                    replica_id=replica_id, stats=dict(stats)
                ),
                what="serve_stats",
            )
        except Exception:  # noqa: BLE001 — telemetry must not kill
            # the replica loop
            logger.debug("serve stats report failed", exc_info=True)

    def query_traces(
        self,
        trace_id: str = "",
        subject: str = "",
        limit: int = 0,
        max_wait: Optional[float] = None,
    ) -> msg.TraceQueryResponse:
        """Assembled distributed-trace timelines from the master's
        trace store. ``trace_id`` fetches one trace; ``subject``
        filters by membership (a serving request id, or
        ``node:<id>``); ``limit`` > 0 keeps the newest N. The
        ``obs_report --trace`` feed."""
        return self._get(
            msg.TraceQueryRequest(
                trace_id=trace_id, subject=subject, limit=limit
            ),
            max_wait=max_wait,
        )

    def query_serving(
        self, max_wait: Optional[float] = None
    ) -> msg.ServeQueryResponse:
        """The router's serving snapshot (per-replica health/stats,
        request counters, QPS/p99) — obs_report --serving's feed."""
        return self._get(
            msg.ServeQueryRequest(), max_wait=max_wait
        )

    # -- multi-job pool plane ---------------------------------------------

    def pool_submit(
        self,
        job_id: str,
        tenant: str = "default",
        priority: int = 0,
        n_slices: int = 1,
        min_slices: int = 0,
        queue: str = "default",
    ) -> msg.PoolSubmitResponse:
        """Submit a job to the pool master's gang scheduler.
        Idempotent on ``job_id`` (a resubmission returns the job's
        current state instead of double-queueing)."""
        return self._get(
            msg.PoolSubmitRequest(
                job_id=job_id,
                tenant=tenant,
                priority=priority,
                n_slices=n_slices,
                min_slices=min_slices,
                queue=queue,
            )
        )

    def pool_job_status(
        self, job_id: str, max_wait: Optional[float] = None
    ) -> msg.PoolJobStatusResponse:
        return self._get(
            msg.PoolJobStatusRequest(job_id=job_id),
            max_wait=max_wait,
        )

    def query_pool(
        self, max_wait: Optional[float] = None
    ) -> msg.PoolQueryResponse:
        """The pool scheduler's snapshot (queue depth per band,
        per-tenant quota usage, slice utilization, preemptions,
        wait percentiles) — obs_report --pool's feed."""
        return self._get(msg.PoolQueryRequest(), max_wait=max_wait)

    def query_capacity(
        self, max_wait: Optional[float] = None
    ) -> msg.CapacityQueryResponse:
        """The pool master's capacity accounting rollup (per-tenant
        chip-seconds by state, goodput-per-chip, SLO budget standing)
        — obs_report --capacity's feed."""
        return self._get(
            msg.CapacityQueryRequest(), max_wait=max_wait
        )

    def query_stall(
        self, max_wait: Optional[float] = None
    ) -> msg.StallQueryResponse:
        """The master's stall-localization snapshot (per-host beacon
        progress table, open/recent collective_stall incidents with
        culprit + trace id + capture bundles) — obs_report --stall's
        feed."""
        return self._get(
            msg.StallQueryRequest(), max_wait=max_wait
        )

    def query_metrics(
        self, max_wait: Optional[float] = None
    ) -> str:
        """The master's Prometheus text exposition over the control
        plane (same payload as GET /metrics)."""
        resp = self._get(
            msg.MetricsRequest(node_id=self.node_id),
            max_wait=max_wait,
        )
        return resp.text

    # -- PS-elastic sparse path ------------------------------------------

    @retry()
    def get_partition_map(self):
        """Fetch the current embedding PartitionMap (sparse path)."""
        from dlrover_tpu.sparse.partition import PartitionMap

        resp = self._get(msg.PartitionMapRequest())
        return PartitionMap(
            version=resp.version,
            assignment=list(resp.assignment),
            ps_addrs={int(k): v for k, v in resp.ps_addrs.items()},
        )

    @retry()
    def register_ps(self, ps_id: int, addr: str):
        self._report(
            msg.PsRegisterRequest(node_id=ps_id, addr=addr)
        )

    def report_ps_stats(self, ps_id: int, qps: float,
                        cpu_percent: float, total_rows: int):
        try:
            self._client.report(msg.PsStatsReport(
                node_id=ps_id, qps=qps, cpu_percent=cpu_percent,
                total_rows=total_rows,
            ), wait_for_ready=False)
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def close(self):
        self._client.close()
