"""Typed client to the job master, used by agents and trainers.

Parity: dlrover/python/elastic_agent/master_client.py:49 (MasterClient
with the retry decorator at :26), re-typed onto the msgpack schema.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.constants import (
    NodeAction,
    NodeEnv,
    RendezvousName,
)
from dlrover_tpu.common.log import get_logger

logger = get_logger("master_client")


def retry(times: int = 3, interval: float = 1.0):
    def decorator(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            last_exc: Optional[Exception] = None
            for attempt in range(times):
                try:
                    return fn(*args, **kwargs)
                except Exception as e:  # noqa: BLE001
                    last_exc = e
                    logger.warning(
                        "%s failed (attempt %d/%d): %s",
                        fn.__name__,
                        attempt + 1,
                        times,
                        e,
                    )
                    time.sleep(interval * (attempt + 1))
            raise last_exc  # type: ignore[misc]

        return wrapped

    return decorator


class MasterClient:
    """One instance per process; safe to share across threads."""

    _singleton: Optional["MasterClient"] = None

    def __init__(self, addr: str, node_id: int = 0, node_rank: int = -1):
        self._client = RpcClient(addr)
        self.node_id = node_id
        self.node_rank = node_rank if node_rank >= 0 else node_id

    @classmethod
    def singleton(cls) -> "MasterClient":
        if cls._singleton is None:
            addr = os.getenv(NodeEnv.MASTER_ADDR, "")
            if not addr:
                raise RuntimeError(
                    f"{NodeEnv.MASTER_ADDR} not set; is this process "
                    "running under dlrover-tpu-run?"
                )
            node_id = int(os.getenv(NodeEnv.NODE_ID, "0"))
            node_rank = int(os.getenv(NodeEnv.NODE_RANK, "-1"))
            cls._singleton = cls(addr, node_id, node_rank)
        return cls._singleton

    @classmethod
    def reset(cls) -> None:
        cls._singleton = None

    # -- node lifecycle -----------------------------------------------------

    @retry()
    def register_node(self, node_type: str = "worker", node_ip: str = ""):
        self._client.report(
            msg.NodeAddressRequest(
                node_id=self.node_id, node_type=node_type, node_ip=node_ip
            )
        )

    @retry()
    def report_failure(
        self,
        error_data: str,
        level: str,
        restart_count: int = 0,
        fatal: bool = False,
        diagnostics: str = "",
    ) -> str:
        resp = self._client.report(
            msg.NodeFailureReport(
                node_id=self.node_id,
                error_data=error_data,
                level=level,
                restart_count=restart_count,
                fatal=fatal,
                diagnostics=diagnostics,
            )
        )
        return resp.action if resp else NodeAction.RESTART_IN_PLACE

    @retry()
    def report_succeeded(self):
        self._client.report(
            msg.NodeSucceededReport(node_id=self.node_id)
        )

    def heartbeat(self) -> str:
        resp = self._client.report(
            msg.HeartbeatRequest(node_id=self.node_id, timestamp=time.time())
        )
        return resp.action if resp else "none"

    # -- rendezvous ---------------------------------------------------------

    @retry()
    def join_rendezvous(
        self,
        local_world_size: int,
        rdzv_name: str = RendezvousName.TRAINING,
    ) -> int:
        resp = self._client.get(
            msg.JoinRendezvousRequest(
                node_id=self.node_id,
                node_rank=self.node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
            )
        )
        return resp.round

    def get_comm_world(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> Tuple[int, int, Dict[int, int]]:
        resp = self._client.get(
            msg.CommWorldRequest(
                node_id=self.node_id,
                node_rank=self.node_rank,
                rdzv_name=rdzv_name,
            )
        )
        return resp.round, resp.group, resp.world

    def num_nodes_waiting(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> int:
        try:
            resp = self._client.get(
                msg.WaitingNodeNumRequest(
                    node_id=self.node_id, rdzv_name=rdzv_name
                )
            )
            return resp.waiting_num
        except Exception:  # noqa: BLE001 - polling must not kill the agent
            return 0

    @retry()
    def report_network_check(self, normal: bool, elapsed_time: float):
        self._client.report(
            msg.NetworkCheckResultRequest(
                node_id=self.node_rank,
                normal=normal,
                elapsed_time=elapsed_time,
            )
        )

    def query_fault_nodes(self) -> Tuple[List[int], str]:
        resp = self._client.get(msg.NetworkCheckQueryRequest(kind="fault"))
        return resp.nodes, resp.reason

    def query_stragglers(self) -> Tuple[List[int], str]:
        resp = self._client.get(
            msg.NetworkCheckQueryRequest(kind="straggler")
        )
        return resp.nodes, resp.reason

    # -- kv store -----------------------------------------------------------

    @retry()
    def kv_set(self, key: str, value: bytes):
        self._client.report(msg.KVStoreSetRequest(key=key, value=value))

    def kv_get(self, key: str) -> Optional[bytes]:
        resp = self._client.get(msg.KVStoreGetRequest(key=key))
        return resp.value if resp.found else None

    def kv_add(self, key: str, amount: int) -> int:
        resp = self._client.get(
            msg.KVStoreAddRequest(key=key, amount=amount)
        )
        return resp.value

    def kv_wait(self, key: str, timeout: float = 120.0) -> bytes:
        deadline = time.time() + timeout
        while time.time() < deadline:
            value = self.kv_get(key)
            if value is not None:
                return value
            time.sleep(0.2)
        raise TimeoutError(f"kv key {key!r} not set within {timeout}s")

    # -- data sharding ------------------------------------------------------

    @retry()
    def create_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        task_type: str = "training",
    ):
        self._client.report(
            msg.DatasetShardParams(
                batch_size=batch_size,
                num_epochs=num_epochs,
                dataset_size=dataset_size,
                shuffle=shuffle,
                num_minibatches_per_shard=num_minibatches_per_shard,
                dataset_name=dataset_name,
                task_type=task_type,
                storage_type=storage_type,
            )
        )

    def get_task(self, dataset_name: str) -> msg.Task:
        return self._client.get(
            msg.TaskRequest(node_id=self.node_id, dataset_name=dataset_name)
        )

    @retry()
    def report_task_result(
        self, dataset_name: str, task_id: int, success: bool = True
    ):
        self._client.report(
            msg.TaskResultRequest(
                node_id=self.node_id,
                dataset_name=dataset_name,
                task_id=task_id,
                success=success,
            )
        )

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._client.get(
            msg.ShardCheckpointRequest(dataset_name=dataset_name)
        )
        return resp.content

    @retry()
    def restore_shard_checkpoint(self, dataset_name: str, content: str):
        self._client.report(
            msg.RestoreShardRequest(dataset_name=dataset_name, content=content)
        )

    # -- auto-tuning --------------------------------------------------------

    def get_parallel_config(self):
        """Master-pushed tuning config (ref ParalConfigTuner)."""
        return self._client.get(
            msg.ParallelConfigRequest(node_id=self.node_id)
        )

    # -- metrics ------------------------------------------------------------

    def report_step(self, step: int, tokens: int = 0):
        try:
            self._client.report(
                msg.StepReport(
                    node_id=self.node_id,
                    timestamp=time.time(),
                    step=step,
                    tokens=tokens,
                )
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def report_resource(
        self,
        cpu_percent: float,
        memory_mb: int,
        hbm_used_gb: float = 0.0,
        duty_cycle: float = 0.0,
    ):
        try:
            self._client.report(
                msg.ResourceStats(
                    node_id=self.node_id,
                    cpu_percent=cpu_percent,
                    memory_mb=memory_mb,
                    hbm_used_gb=hbm_used_gb,
                    duty_cycle=duty_cycle,
                )
            )
        except Exception:  # noqa: BLE001
            pass

    def report_metrics_snapshot(
        self,
        host: str,
        registry: Optional[dict] = None,
        resource: Optional[dict] = None,
        step_times: Optional[list] = None,
        events: Optional[list] = None,
        timestamp: Optional[float] = None,
    ):
        """Ship this host's telemetry snapshot to the master's
        FleetAggregator (ResourceMonitor cadence). Best-effort like
        every other telemetry report."""
        try:
            self._client.report(
                msg.MetricsSnapshotReport(
                    node_id=self.node_id,
                    host=host,
                    timestamp=(
                        timestamp if timestamp is not None else time.time()
                    ),
                    registry=registry or {},
                    resource=resource or {},
                    step_times=list(step_times or []),
                    events=list(events or []),
                )
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    # -- forensics ----------------------------------------------------------

    def report_diagnostics(
        self, kind: str, bundle_path: str = "", digest: str = ""
    ):
        """Ship a forensics digest (hang / crash / on-demand diagnose)
        to the master's per-node history. Best-effort: forensics must
        never block or fail the recovery path it documents."""
        try:
            self._client.report(
                msg.DiagnosticsReport(
                    node_id=self.node_id,
                    kind=kind,
                    bundle_path=bundle_path,
                    digest=digest,
                    timestamp=time.time(),
                )
            )
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            logger.warning(
                "could not ship %s diagnostics to master", kind,
                exc_info=True,
            )

    def query_diagnostics(self, node_id: int = -1) -> List:
        """The master's stored DiagnosticsReport history (tools)."""
        resp = self._client.get(
            msg.DiagnosticsQueryRequest(node_id=node_id)
        )
        return list(resp.reports)

    # -- PS-elastic sparse path ------------------------------------------

    @retry()
    def get_partition_map(self):
        """Fetch the current embedding PartitionMap (sparse path)."""
        from dlrover_tpu.sparse.partition import PartitionMap

        resp = self._client.get(msg.PartitionMapRequest())
        return PartitionMap(
            version=resp.version,
            assignment=list(resp.assignment),
            ps_addrs={int(k): v for k, v in resp.ps_addrs.items()},
        )

    @retry()
    def register_ps(self, ps_id: int, addr: str):
        self._client.report(
            msg.PsRegisterRequest(node_id=ps_id, addr=addr)
        )

    def report_ps_stats(self, ps_id: int, qps: float,
                        cpu_percent: float, total_rows: int):
        try:
            self._client.report(msg.PsStatsReport(
                node_id=ps_id, qps=qps, cpu_percent=cpu_percent,
                total_rows=total_rows,
            ))
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            pass

    def close(self):
        self._client.close()
