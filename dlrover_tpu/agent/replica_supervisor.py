"""Agent-side replica supervisor: process supervision for the
serving plane.

The training agent supervises a trainer process (restart budgets,
failure classification); this is the same idea for a serving replica:
spawn ``python -m dlrover_tpu.serving.replica`` as a child process,
watch it, and relaunch on exit within a bounded budget — so the
remediation ladder's *restart* rung has a real executor on the host
(the master pushes ``restart_training`` on the replica's heartbeat;
the in-process worker bounces itself, and if the whole process died,
this supervisor brings a fresh incarnation up, which re-registers and
triggers the router's requeue-on-reregistration).

Kept deliberately simple (no exit classification — a replica crash
is always relaunchable until the budget runs out): serving has no
shard ledger to corrupt, the router's request ledger owns all
durable state.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.config import ensure_framework_on_pythonpath
from dlrover_tpu.common.log import get_logger

logger = get_logger("agent.replica_supervisor")

_RESTARTS_TOTAL = obs.counter(
    "dlrover_serve_replica_restarts_total",
    "Replica process relaunches by the agent-side supervisor, by "
    "reason (exit / action)",
    ("reason",),
)


class ReplicaSupervisor:
    def __init__(
        self,
        master_addr: str,
        replica_id: int,
        seed: int = 0,
        max_restarts: int = 3,
        restart_backoff_s: float = 1.0,
        extra_args: Optional[List[str]] = None,
        env: Optional[dict] = None,
        poll_interval: float = 0.2,
        role: str = "mixed",
    ):
        self.master_addr = master_addr
        self.replica_id = replica_id
        self.seed = seed
        # Disaggregation role the spawned replica registers with
        # (prefill / decode / mixed) — a supervisor relaunch must
        # bring the SAME role back, or the fleet changes shape.
        self.role = role
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.extra_args = list(extra_args or [])
        self._env = env
        self.poll_interval = poll_interval
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _command(self) -> List[str]:
        return [
            sys.executable,
            "-m",
            "dlrover_tpu.serving.replica",
            "--master", self.master_addr,
            "--replica_id", str(self.replica_id),
            "--seed", str(self.seed),
            "--role", self.role,
            *self.extra_args,
        ]

    def spawn(self) -> subprocess.Popen:
        env = ensure_framework_on_pythonpath(
            dict(self._env if self._env is not None else os.environ)
        )
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            self._command(),
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        obs.event(
            "serve.replica_spawn",
            replica_id=self.replica_id, pid=self.proc.pid,
        )
        logger.info(
            "replica %d spawned (pid %d)",
            self.replica_id, self.proc.pid,
        )
        return self.proc

    def restart(self, reason: str = "action") -> None:
        """Kill + respawn (the process-level restart rung). Counts
        against the same budget as crash relaunches."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        self.restarts += 1
        _RESTARTS_TOTAL.inc(reason=reason)
        self.spawn()

    # -- supervision loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        if self.proc is None:
            self.spawn()
        self._thread = threading.Thread(
            target=self._watch,
            name=f"replica-supervisor-{self.replica_id}",
            daemon=True,
        )
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            proc = self.proc
            if proc is None or proc.poll() is None:
                continue
            if self.restarts >= self.max_restarts:
                logger.error(
                    "replica %d exited rc=%s past its restart "
                    "budget (%d); giving up — the master's watchdog "
                    "will declare the node dead and requeue",
                    self.replica_id, proc.returncode,
                    self.max_restarts,
                )
                obs.event(
                    "serve.replica_budget_exhausted",
                    replica_id=self.replica_id,
                    rc=proc.returncode,
                )
                return
            logger.warning(
                "replica %d exited rc=%s; relaunching (%d/%d)",
                self.replica_id, proc.returncode,
                self.restarts + 1, self.max_restarts,
            )
            self._stop.wait(self.restart_backoff_s)
            if self._stop.is_set():
                return
            self.restarts += 1
            _RESTARTS_TOTAL.inc(reason="exit")
            self.spawn()

    def stop(self, kill: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if kill and self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def wait_until(
    predicate, timeout: float = 30.0, interval: float = 0.1
) -> bool:
    """Poll ``predicate`` until truthy or timeout (drill helper)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False
