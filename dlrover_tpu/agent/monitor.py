"""Agent-side monitors: node resources and training progress.

Parity with the reference's agent monitors
(dlrover/python/elastic_agent/monitor/resource.py:90 ResourceMonitor —
psutil + pynvml telemetry pushed to the master; monitor/training.py:79
TorchTrainingMonitor — global-step reports feeding the master's speed
monitor). TPU adaptation: chip telemetry comes from JAX's
``local_devices()[i].memory_stats()`` (HBM in use) instead of pynvml,
and the training side reads the metrics file the trainer process
writes (same file-drop mechanism as the reference's
ConfigPath.RUNTIME_METRICS).
"""

from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time
from typing import Dict, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs import beacon as beacon_mod

logger = get_logger("agent_monitor")

# How many of the trainer's most recent per-step wall times ride the
# metrics file (and from there the master's fleet snapshot).
RECENT_STEP_TIMES = 32

METRICS_FILE_ENV = "DLROVER_TPU_METRICS_FILE"
PHASES_FILE_ENV = "DLROVER_TPU_PHASES_FILE"

# Local staleness threshold before the agent treats the co-hosted
# trainer's beacon as wedged and fires its forensics hook. Sits above
# any sane step time but well under the master's heartbeat timeout,
# so the host-local SIGUSR1 capture lands while the wedge is live.
BEACON_STALL_ENV = "DLROVER_TPU_BEACON_STALL_S"
DEFAULT_BEACON_STALL_S = 120.0


def default_metrics_file() -> str:
    """Job-scoped path (same rule as paral_config_tuner.
    default_config_file): two jobs on one host must not cross-talk the
    hang detector and step/speed reports."""
    job = os.getenv("DLROVER_TPU_JOB_NAME", "default")
    return f"/tmp/dlrover_tpu_train_metrics_{job}.json"


def current_resource_stats() -> dict:
    """One sample of host + TPU utilization."""
    stats = {
        "cpu_percent": 0.0,
        "memory_mb": 0,
        "hbm_used_gb": 0.0,
        "duty_cycle": 0.0,
    }
    try:
        import psutil

        stats["cpu_percent"] = psutil.cpu_percent(interval=None)
        stats["memory_mb"] = int(
            psutil.Process().memory_info().rss / (1 << 20)
        )
    except Exception:  # noqa: BLE001 — psutil optional
        pass
    try:
        import jax

        hbm = 0
        for dev in jax.local_devices():
            ms = dev.memory_stats() or {}
            hbm += ms.get("bytes_in_use", 0)
        stats["hbm_used_gb"] = hbm / (1 << 30)
    except Exception:  # noqa: BLE001 — no device / not initialized
        pass
    return stats


class ResourceMonitor:
    """Samples resources and reports them to the master.

    Each report also ships a fleet-telemetry snapshot: this process's
    obs registry dump, the trainer's recent per-step wall times (read
    from the step-metrics file the training process writes), a derived
    tokens/s, and any tracer events new since the previous snapshot —
    the agent half of the master's FleetAggregator."""

    def __init__(
        self,
        client,
        interval: float = 30.0,
        metrics_file: Optional[str] = None,
        beacon_path: Optional[str] = None,
        on_stale_beacon=None,
    ):
        self.client = client
        self.interval = interval
        self.metrics_file = metrics_file or os.getenv(
            METRICS_FILE_ENV, default_metrics_file()
        )
        # Stall beacon: each snapshot ships the trainer's last
        # progress stamp + locally-computed staleness; a stamp older
        # than the stall threshold fires on_stale_beacon(stamp) once
        # per distinct wedge (the agent wires its SIGUSR1 forensics
        # capture here).
        self.beacon_path = beacon_path or beacon_mod.beacon_file()
        self.on_stale_beacon = on_stale_beacon
        try:
            self.beacon_stall_s = float(
                os.getenv(BEACON_STALL_ENV, "")
                or DEFAULT_BEACON_STALL_S
            )
        except ValueError:
            self.beacon_stall_s = DEFAULT_BEACON_STALL_S
        self._stall_fired_key: Optional[tuple] = None
        self.host = (
            os.getenv("DLROVER_TPU_HOST_IP", "")
            or socket.gethostname()
            or f"node{getattr(client, 'node_id', -1)}"
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # snapshot bookkeeping: send each step time / event only once
        self._last_snapshot_step = -1
        self._event_tracer = None
        self._event_cursor = 0
        # When the host traces to a file, EVERY process on the host
        # (this agent AND the training process it supervises) appends
        # to that one file — tailing it is how trainer-side spans
        # (steps, ckpt stages, prefetch waits, compile marks) reach
        # the master's goodput accountant.
        from dlrover_tpu.obs.tracer import TRACE_FILE_ENV

        self._trace_path = os.getenv(TRACE_FILE_ENV, "")
        # Start at the file's CURRENT end: the sink appends across
        # agent restarts, and the previous incarnation already shipped
        # the history — replaying it would double-count goodput.
        self._trace_offset = 0
        if self._trace_path:
            try:
                self._trace_offset = os.path.getsize(self._trace_path)
            except OSError:
                pass
        self._last_tokens: Optional[tuple] = None  # (ts, tokens)

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="resource-monitor", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _read_trainer_metrics(self) -> dict:
        try:
            with open(self.metrics_file) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return {}
        return data if isinstance(data, dict) else {}

    def _new_step_times(self, data: dict) -> list:
        step = int(data.get("step", -1))
        recent = [
            float(t)
            for t in data.get("recent_step_times", [])
            if isinstance(t, (int, float)) and t > 0
        ]
        if step < 0:
            return []
        if step <= self._last_snapshot_step:
            # Trainer restarted at a lower step: re-baseline.
            if step < self._last_snapshot_step:
                self._last_snapshot_step = step
            return []
        new = min(step - self._last_snapshot_step, len(recent))
        self._last_snapshot_step = step
        return recent[-new:] if new > 0 else []

    def _tokens_per_s(self, data: dict) -> Optional[float]:
        ts = data.get("ts")
        tokens = data.get("tokens")
        if ts is None or tokens is None:
            return None
        prev, self._last_tokens = self._last_tokens, (ts, tokens)
        if prev is None:
            return None
        dt = float(ts) - float(prev[0])
        dtok = float(tokens) - float(prev[1])
        if dt <= 0 or dtok < 0:
            return None
        return dtok / dt

    # Per-snapshot bound on tailed trace bytes / parsed events, so a
    # chatty trainer cannot balloon one RPC.
    MAX_TRACE_TAIL_BYTES = 1 << 20
    MAX_EVENTS_PER_SNAPSHOT = 5000

    def _tail_trace_events(self) -> list:
        """New complete JSONL lines of the shared trace file since the
        last snapshot (byte-offset cursor; resets on truncation)."""
        try:
            size = os.path.getsize(self._trace_path)
        except OSError:
            return []
        if size < self._trace_offset:
            self._trace_offset = 0  # file truncated/recreated
        if size <= self._trace_offset:
            return []
        try:
            with open(self._trace_path, "rb") as f:
                f.seek(self._trace_offset)
                chunk = f.read(self.MAX_TRACE_TAIL_BYTES)
        except OSError:
            return []
        last_nl = chunk.rfind(b"\n")
        if last_nl < 0:
            return []  # torn line in flight; retry next snapshot
        # Consume only as far as the event cap: the cursor must not
        # skip lines this snapshot didn't ship — the surplus waits
        # for the next snapshot instead of being dropped.
        data = chunk[: last_nl + 1]
        events = []
        consumed = 0
        while (
            consumed < len(data)
            and len(events) < self.MAX_EVENTS_PER_SNAPSHOT
        ):
            nl = data.index(b"\n", consumed)
            line = data[consumed:nl]
            consumed = nl + 1
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "name" in rec and "ts" in rec:
                events.append(rec)
        self._trace_offset += consumed
        return events

    def _new_events(self) -> list:
        if self._trace_path:
            # The in-memory ring would only cover this agent process;
            # the file covers every process on the host (no dupes:
            # agent events are in the file too, so the ring is
            # skipped entirely).
            return self._tail_trace_events()
        tracer = obs.get_tracer()
        if tracer is None:
            return []
        if tracer is not self._event_tracer:
            # configure_tracer replaced the instance: restart the
            # arrival cursor.
            self._event_tracer = tracer
            self._event_cursor = 0
        events, self._event_cursor = tracer.events_since(
            self._event_cursor
        )
        return events[-self.MAX_EVENTS_PER_SNAPSHOT:]

    def build_snapshot(self, stats: Optional[dict] = None) -> dict:
        """The MetricsSnapshotReport payload (sans node_id), exposed
        for tests and for trainers that report their own registry."""
        resource = dict(stats or current_resource_stats())
        data = self._read_trainer_metrics()
        tps = self._tokens_per_s(data)
        if tps is not None:
            resource["tokens_per_s"] = tps
        mfu = data.get("mfu")
        if isinstance(mfu, (int, float)) and mfu > 0:
            resource["mfu"] = float(mfu)
        return {
            "host": self.host,
            "registry": obs.get_registry().dump(),
            "resource": resource,
            "step_times": self._new_step_times(data),
            "events": self._new_events(),
            "beacon": self.beacon_payload(),
        }

    def beacon_payload(self) -> dict:
        """The trainer's last progress stamp plus its staleness age
        on this host's monotonic clock (the writer may be wedged —
        only the file is consulted). Empty when no beacon exists."""
        stamp = beacon_mod.read_beacon(self.beacon_path)
        if not stamp:
            return {}
        age = beacon_mod.stamp_age(stamp)
        out = dict(stamp)
        out["age_s"] = round(age, 3) if age is not None else -1.0
        return out

    def check_beacon_stall(self, stamp: dict) -> bool:
        """Fire the forensics hook when the local beacon is wedged;
        re-arms as soon as the stamp advances. Returns True when the
        hook fired this call."""
        if self.on_stale_beacon is None or not stamp:
            return False
        age = stamp.get("age_s")
        if not isinstance(age, (int, float)) or age < self.beacon_stall_s:
            self._stall_fired_key = None
            return False
        key = (stamp.get("pid"), stamp.get("seq"))
        if key == self._stall_fired_key:
            return False
        self._stall_fired_key = key
        try:
            self.on_stale_beacon(dict(stamp))
        except Exception:  # noqa: BLE001 — capture is best-effort
            logger.warning("stale-beacon hook failed", exc_info=True)
        return True

    def report_once(self) -> dict:
        stats = current_resource_stats()
        try:
            self.client.report_resource(**stats)
        except Exception:  # noqa: BLE001
            logger.debug("resource report failed", exc_info=True)
        snap = self.build_snapshot(stats)
        try:
            self.client.report_metrics_snapshot(**snap)
        except Exception:  # noqa: BLE001 — fleet telemetry is
            # best-effort (and test fakes may lack the method)
            logger.debug("metrics snapshot failed", exc_info=True)
        self.check_beacon_stall(snap.get("beacon") or {})
        return stats

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.report_once()


class TrainingMonitor:
    """Relays the trainer's step metrics file to the master speed
    monitor (ref TorchTrainingMonitor.report_resource_with_step,
    elastic_agent/monitor/training.py:79)."""

    def __init__(
        self,
        client,
        metrics_file: Optional[str] = None,
        interval: float = 15.0,
    ):
        self.client = client
        self.metrics_file = metrics_file or os.getenv(
            METRICS_FILE_ENV, default_metrics_file()
        )
        self.interval = interval
        self._last_step = -1
        self._last_tokens = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # Per-process rolling window of recent step wall times, keyed by
    # metrics-file path (write_metrics is a staticmethod; the trainer
    # process owns exactly one window per file).
    _recent_step_times: Dict[str, "collections.deque"] = {}

    @staticmethod
    def write_metrics(
        step: int,
        tokens: int = 0,
        path: Optional[str] = None,
        step_time: Optional[float] = None,
        mfu: Optional[float] = None,
    ) -> None:
        """Called from the TRAINING process each step (cheap: one
        tmp-file rename). ``step_time`` — this step's wall time, when
        the loop measures it — accumulates into a rolling
        ``recent_step_times`` window the agent forwards to the
        master's straggler scorer. ``mfu`` — the trainer's live
        model-FLOPs-utilisation — rides the same file into the
        agent's fleet snapshot (resource ``mfu``), so the master can
        aggregate utilisation across hosts."""
        obs.event("trainer.step", step=step, tokens=tokens)
        # Last-known-step into the black box: one dict update, so a
        # crash bundle can say how far training got even when the
        # metrics file is gone with the container.
        obs.recorder_note(step=step, tokens=tokens)
        path = path or os.getenv(METRICS_FILE_ENV, default_metrics_file())
        recent = TrainingMonitor._recent_step_times.setdefault(
            path, collections.deque(maxlen=RECENT_STEP_TIMES)
        )
        if step_time is not None and step_time > 0:
            recent.append(round(float(step_time), 6))
        data = {
            "step": step,
            "tokens": tokens,
            "ts": time.time(),
            "recent_step_times": list(recent),
        }
        if mfu is not None and mfu > 0:
            data["mfu"] = round(float(mfu), 6)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    @staticmethod
    def mark_phase(name: str, path: Optional[str] = None) -> None:
        """Timestamp a startup/recovery phase boundary from the
        TRAINING process (proc_start, dist_ready, built, restore_done,
        first_step_done, ...). Written only when
        DLROVER_TPU_PHASES_FILE is set (or ``path`` given) — chaos
        drills use the marks to break a recovery time into
        explainable, budget-checkable segments. Each trainer (re)start
        overwrites the file from its own proc_start, so the file
        always describes the LATEST attempt."""
        # Mirror every mark into the obs tracer (its own env gate,
        # DLROVER_TPU_TRACE_FILE): the recovery-timeline reconstructor
        # (obs/timeline.py) folds these "trainer.<mark>" events into
        # the canonical failure-detect/rendezvous/restore/first-step
        # breakdown. No-op when tracing is off.
        obs.event(f"trainer.{name}")
        path = path or os.getenv(PHASES_FILE_ENV)
        if not path:
            return
        marks = {}
        if name != "proc_start":
            try:
                with open(path) as f:
                    marks = json.load(f)
            except (OSError, ValueError):
                marks = {}
        marks[name] = time.time()
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(marks, f)
        os.replace(tmp, path)

    def report_once(self) -> Optional[int]:
        try:
            with open(self.metrics_file) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        step = int(data.get("step", -1))
        if step == self._last_step:
            return None
        if step < self._last_step:
            # Training process restarted at an earlier step (resume
            # from checkpoint / from scratch): re-baseline instead of
            # going silent until the old high-water mark is passed.
            self._last_tokens = 0
        self._last_step = step
        # The metrics file carries a CUMULATIVE token count; the
        # master's speed monitor accumulates per-report deltas.
        tokens = int(data.get("tokens", 0))
        delta = max(tokens - self._last_tokens, 0)
        self._last_tokens = tokens
        try:
            self.client.report_step(step, delta)
        except Exception:  # noqa: BLE001
            logger.debug("step report failed", exc_info=True)
        return step

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="training-monitor", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.report_once()
