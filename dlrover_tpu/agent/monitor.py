"""Agent-side monitors: node resources and training progress.

Parity with the reference's agent monitors
(dlrover/python/elastic_agent/monitor/resource.py:90 ResourceMonitor —
psutil + pynvml telemetry pushed to the master; monitor/training.py:79
TorchTrainingMonitor — global-step reports feeding the master's speed
monitor). TPU adaptation: chip telemetry comes from JAX's
``local_devices()[i].memory_stats()`` (HBM in use) instead of pynvml,
and the training side reads the metrics file the trainer process
writes (same file-drop mechanism as the reference's
ConfigPath.RUNTIME_METRICS).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from dlrover_tpu import obs
from dlrover_tpu.common.log import get_logger

logger = get_logger("agent_monitor")

METRICS_FILE_ENV = "DLROVER_TPU_METRICS_FILE"
PHASES_FILE_ENV = "DLROVER_TPU_PHASES_FILE"


def default_metrics_file() -> str:
    """Job-scoped path (same rule as paral_config_tuner.
    default_config_file): two jobs on one host must not cross-talk the
    hang detector and step/speed reports."""
    job = os.getenv("DLROVER_TPU_JOB_NAME", "default")
    return f"/tmp/dlrover_tpu_train_metrics_{job}.json"


def current_resource_stats() -> dict:
    """One sample of host + TPU utilization."""
    stats = {
        "cpu_percent": 0.0,
        "memory_mb": 0,
        "hbm_used_gb": 0.0,
        "duty_cycle": 0.0,
    }
    try:
        import psutil

        stats["cpu_percent"] = psutil.cpu_percent(interval=None)
        stats["memory_mb"] = int(
            psutil.Process().memory_info().rss / (1 << 20)
        )
    except Exception:  # noqa: BLE001 — psutil optional
        pass
    try:
        import jax

        hbm = 0
        for dev in jax.local_devices():
            ms = dev.memory_stats() or {}
            hbm += ms.get("bytes_in_use", 0)
        stats["hbm_used_gb"] = hbm / (1 << 30)
    except Exception:  # noqa: BLE001 — no device / not initialized
        pass
    return stats


class ResourceMonitor:
    """Samples resources and reports them to the master."""

    def __init__(self, client, interval: float = 30.0):
        self.client = client
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="resource-monitor", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def report_once(self) -> dict:
        stats = current_resource_stats()
        try:
            self.client.report_resource(**stats)
        except Exception:  # noqa: BLE001
            logger.debug("resource report failed", exc_info=True)
        return stats

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.report_once()


class TrainingMonitor:
    """Relays the trainer's step metrics file to the master speed
    monitor (ref TorchTrainingMonitor.report_resource_with_step,
    elastic_agent/monitor/training.py:79)."""

    def __init__(
        self,
        client,
        metrics_file: Optional[str] = None,
        interval: float = 15.0,
    ):
        self.client = client
        self.metrics_file = metrics_file or os.getenv(
            METRICS_FILE_ENV, default_metrics_file()
        )
        self.interval = interval
        self._last_step = -1
        self._last_tokens = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def write_metrics(
        step: int, tokens: int = 0, path: Optional[str] = None
    ) -> None:
        """Called from the TRAINING process each step (cheap: one
        tmp-file rename)."""
        obs.event("trainer.step", step=step, tokens=tokens)
        path = path or os.getenv(METRICS_FILE_ENV, default_metrics_file())
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"step": step, "tokens": tokens, "ts": time.time()}, f
            )
        os.replace(tmp, path)

    @staticmethod
    def mark_phase(name: str, path: Optional[str] = None) -> None:
        """Timestamp a startup/recovery phase boundary from the
        TRAINING process (proc_start, dist_ready, built, restore_done,
        first_step_done, ...). Written only when
        DLROVER_TPU_PHASES_FILE is set (or ``path`` given) — chaos
        drills use the marks to break a recovery time into
        explainable, budget-checkable segments. Each trainer (re)start
        overwrites the file from its own proc_start, so the file
        always describes the LATEST attempt."""
        # Mirror every mark into the obs tracer (its own env gate,
        # DLROVER_TPU_TRACE_FILE): the recovery-timeline reconstructor
        # (obs/timeline.py) folds these "trainer.<mark>" events into
        # the canonical failure-detect/rendezvous/restore/first-step
        # breakdown. No-op when tracing is off.
        obs.event(f"trainer.{name}")
        path = path or os.getenv(PHASES_FILE_ENV)
        if not path:
            return
        marks = {}
        if name != "proc_start":
            try:
                with open(path) as f:
                    marks = json.load(f)
            except (OSError, ValueError):
                marks = {}
        marks[name] = time.time()
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(marks, f)
        os.replace(tmp, path)

    def report_once(self) -> Optional[int]:
        try:
            with open(self.metrics_file) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        step = int(data.get("step", -1))
        if step == self._last_step:
            return None
        if step < self._last_step:
            # Training process restarted at an earlier step (resume
            # from checkpoint / from scratch): re-baseline instead of
            # going silent until the old high-water mark is passed.
            self._last_tokens = 0
        self._last_step = step
        # The metrics file carries a CUMULATIVE token count; the
        # master's speed monitor accumulates per-report deltas.
        tokens = int(data.get("tokens", 0))
        delta = max(tokens - self._last_tokens, 0)
        self._last_tokens = tokens
        try:
            self.client.report_step(step, delta)
        except Exception:  # noqa: BLE001
            logger.debug("step report failed", exc_info=True)
        return step

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="training-monitor", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.report_once()
