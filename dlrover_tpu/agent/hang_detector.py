"""Training-hang detection (agent side).

Parity with atorch's fault-tolerance hang detector
(atorch/fault_tolerance/hanging_detector.py:86 + custom_agent.py:19
LocalDetectHangingAgent): the torch version has every rank write a
heartbeat tensor through the c10d store and relaunches workers when it
stalls. Here the signal is the step-metrics file the training process
already writes (agent/monitor.py TrainingMonitor.write_metrics) — a
training process that is alive but making no step progress for
``hang_timeout`` seconds is hung (deadlocked collective, stuck host
callback, wedged TPU runtime) and gets restarted by the agent.

Distinct from the master's heartbeat timeout (job_manager.py): that
catches dead *agents*; this catches live agents whose *training
process* stopped stepping.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from dlrover_tpu import obs
from dlrover_tpu.agent.monitor import (
    default_metrics_file,
    METRICS_FILE_ENV,
)
from dlrover_tpu.common.log import get_logger

logger = get_logger("hang_detector")

_HANGS_TOTAL = obs.counter(
    "dlrover_hang_detect_total",
    "Training-process hangs detected by the agent (no step progress "
    "within hang_timeout)",
)


class HangDetector:
    """Tracks step progress; ``check()`` returns True when hung.

    ``startup_grace`` covers compilation: the first step legitimately
    takes minutes on TPU (cold jit), so the clock only starts after
    the first step lands or the grace expires.
    """

    def __init__(
        self,
        hang_timeout: float = 600.0,
        startup_grace: float = 1800.0,
        metrics_file: Optional[str] = None,
    ):
        self.hang_timeout = hang_timeout
        self.startup_grace = startup_grace
        self.metrics_file = metrics_file or os.getenv(
            METRICS_FILE_ENV, default_metrics_file()
        )
        self.reset()

    def reset(self) -> None:
        # Monotonic, NOT wall clock: an NTP step would otherwise fake
        # a hang (clock jumps forward) or mask a real one (clock jumps
        # back) — hang detection measures elapsed time, nothing else.
        self._started_at = time.monotonic()
        self._last_step = -1
        self._last_progress = time.monotonic()
        self._hang_reported = False

    def _read_step(self) -> Optional[int]:
        try:
            with open(self.metrics_file) as f:
                return int(json.load(f).get("step", -1))
        except (OSError, ValueError):
            return None

    def check(self) -> bool:
        """True when the training process should be considered hung."""
        now = time.monotonic()
        step = self._read_step()
        # ANY step change counts as progress: a resume may restart at
        # a LOWER step than the previous incarnation's high-water mark
        # (the agent also clears the file on spawn, belt and braces).
        if step is not None and step != self._last_step:
            self._last_step = step
            self._last_progress = now
            self._hang_reported = False
            return False
        if self._last_step < 0:
            # still compiling / warming up
            hung = now - self._started_at > self.startup_grace
        else:
            hung = now - self._last_progress > self.hang_timeout
        if hung and not self._hang_reported:
            # Once per hang (reset()/progress re-arms): the fleet view
            # and recovery timelines must see the hang, not just the
            # restart it triggers.
            self._hang_reported = True
            _HANGS_TOTAL.inc()
            obs.event(
                "agent.hang_detected",
                seconds_since_progress=round(
                    self.seconds_since_progress(), 3
                ),
                last_step=self._last_step,
            )
        return hung

    def seconds_since_progress(self) -> float:
        return time.monotonic() - self._last_progress

    @property
    def last_step(self) -> int:
        """Last step observed before the stall (-1: never stepped)."""
        return self._last_step
