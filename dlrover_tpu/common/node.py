"""Node model and status state machine.

Parity with the reference's node model (dlrover/python/common/node.py:336
`Node`) and status flow (dlrover/python/master/node/status_flow.py), with
TPU-native resources: a node is a *host* of a TPU pod slice owning
``chips`` accelerator chips, not a GPU pod.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
)


@dataclasses.dataclass
class NodeResource:
    """Resources of one host in the job."""

    cpu: float = 0.0
    memory_mb: int = 0
    # TPU chips attached to this host (4 for a v5p host, 8 for v5e-8, ...)
    chips: int = 0
    tpu_type: str = ""  # e.g. "v5p", "v5e"
    # Which TPU slice of a multi-slice job this host belongs to; the
    # scaler keeps replacements in the dead host's slice so the DCN
    # mesh axis stays balanced. -1 = single-slice job (no slice pin in
    # the pod manifest — pinning slice "0" on an unlabeled cluster
    # would leave every pod unschedulable).
    slice_id: int = -1
    # Utilisation telemetry filled in by the agent's resource monitor.
    used_cpu: float = 0.0
    used_memory_mb: int = 0
    hbm_used_gb: float = 0.0
    duty_cycle: float = 0.0  # TPU tensorcore duty cycle [0, 1]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "NodeResource":
        return cls(**{k: v for k, v in d.items() if k in _RESOURCE_FIELDS})


_RESOURCE_FIELDS = {f.name for f in dataclasses.fields(NodeResource)}


# Legal status transitions. Anything not listed here is an error except
# transitions to the same status (idempotent) which are silently allowed.
_VALID_TRANSITIONS = {
    NodeStatus.INITIAL: {
        NodeStatus.PENDING,
        NodeStatus.RUNNING,
        NodeStatus.FAILED,
        NodeStatus.DELETED,
    },
    NodeStatus.PENDING: {
        NodeStatus.RUNNING,
        NodeStatus.SUCCEEDED,
        NodeStatus.FAILED,
        NodeStatus.DELETED,
        NodeStatus.BREAKDOWN,
    },
    NodeStatus.RUNNING: {
        NodeStatus.SUCCEEDED,
        NodeStatus.FAILED,
        NodeStatus.DELETED,
        NodeStatus.BREAKDOWN,
    },
    NodeStatus.SUCCEEDED: {NodeStatus.DELETED},
    NodeStatus.FAILED: {NodeStatus.DELETED},
    NodeStatus.BREAKDOWN: {NodeStatus.DELETED},
    NodeStatus.DELETED: set(),
}


def is_valid_transition(old: str, new: str) -> bool:
    if old == new:
        return True
    return new in _VALID_TRANSITIONS.get(old, set())


@dataclasses.dataclass
class Node:
    """One host participating in a job, as tracked by the master."""

    type: str
    id: int
    rank: int = -1
    name: str = ""
    status: str = NodeStatus.INITIAL
    host_addr: str = ""
    config_resource: Optional[NodeResource] = None
    used_resource: Optional[NodeResource] = None
    create_time: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    relaunch_count: int = 0
    max_relaunch_count: int = 3
    # Training-process failures handled by the node's own agent (the
    # node stayed up; only the process inside restarted).
    process_failure_count: int = 0
    relaunchable: bool = True
    is_released: bool = False
    exit_reason: str = ""
    # Why the previous incarnation died (set on the replacement by
    # _relaunch): lets the auto-scaler grow resources for OOM retries.
    relaunch_reason: str = ""
    critical: bool = False
    heartbeat_time: float = 0.0
    # Straggler / health flags set by the network-check rendezvous.
    is_straggler: bool = False
    is_unhealthy: bool = False
    # Cordoned by the remediation engine: alive and heartbeating, but
    # excluded from rendezvous and not counted toward the auto-scale
    # target (its replacement is); retired once probation confirms
    # recovery, un-cordoned on rollback.
    cordoned: bool = False
    # Role labels (e.g. a serving replica's serving_role): set at
    # registration or by a labeled ensure_role launch; the labeled
    # ensure_role seam counts alive nodes per label set so each role
    # scales independently. Rides node-table snapshots like any
    # field.
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.config_resource is None:
            self.config_resource = NodeResource()
        if self.create_time == 0.0:
            # Monotonic: only ever compared against other monotonic
            # stamps on the same master (pending/heartbeat timeout
            # sweeps) — an NTP step must not fire or mask a timeout.
            self.create_time = time.monotonic()

    def update_status(self, new_status: str) -> bool:
        """Apply a status transition; returns True if state changed."""
        if new_status == self.status:
            return False
        if not is_valid_transition(self.status, new_status):
            return False
        self.status = new_status
        now = time.time()
        if new_status == NodeStatus.RUNNING and self.start_time == 0.0:
            self.start_time = now
        if new_status in NodeStatus.TERMINAL:
            self.finish_time = now
        return True

    def inc_relaunch_count(self) -> None:
        self.relaunch_count += 1

    def exhausted_relaunch(self) -> bool:
        return self.relaunch_count >= self.max_relaunch_count

    def should_relaunch(self) -> bool:
        """Relaunch policy on failure (ref: dist_job_manager.py:489)."""
        if not self.relaunchable or self.is_released:
            return False
        if self.exit_reason in NodeExitReason.NO_RELAUNCH:
            return False
        return not self.exhausted_relaunch()

    def is_alive(self) -> bool:
        return self.status in NodeStatus.ALIVE

    def update_heartbeat(self) -> None:
        self.heartbeat_time = time.monotonic()

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        d = dict(d)
        if isinstance(d.get("config_resource"), dict):
            d["config_resource"] = NodeResource.from_dict(d["config_resource"])
        if isinstance(d.get("used_resource"), dict):
            d["used_resource"] = NodeResource.from_dict(d["used_resource"])
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})
