"""Flash-checkpoint shared-memory staging format.

TPU-native analogue of the reference's SharedMemoryHandler
(dlrover/python/elastic_agent/torch/ckpt_saver.py:232 —
_traverse_copy_to_shm/_write_shared_memory): the training process
flattens a sharded ``jax.Array`` pytree into one POSIX shm segment;
the host agent reads the segment back and persists it without ever
importing jax.  Layout::

    [8B little-endian meta length][msgpack meta][raw tensor bytes...]

meta = {
  "step": int,
  "extra": {...user metadata...},
  "entries": [
    {"name": "params/blocks/wqkv", "dtype": "bfloat16",
     "global_shape": [...], "index": [[start, stop], ...],
     "offset": N, "nbytes": M},
    ...
  ],
}

Each entry is one *addressable shard* of one pytree leaf, tagged with
its slice into the global (logical) array — this is what makes
reshard-on-load work: the loader reassembles global arrays from any
shard layout and re-shards them onto the new mesh, the moral
equivalent of the reference's FSDP reshard-on-restart
(atorch/utils/fsdp_save_util.py).

No jax import at module level: the agent-side saver runs in a process
that must stay light (and must not grab a TPU chip).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import msgpack
import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import SharedMemoryHandle

logger = get_logger("ckpt_shm")

_META_LEN_BYTES = 8

# bfloat16 has no numpy dtype; stage it as raw uint16 words and tag the
# true dtype in meta so the loader can reinterpret via ml_dtypes/jax.
_RAW_DTYPES = {"bfloat16": np.uint16, "float8_e4m3fn": np.uint8,
               "float8_e5m2": np.uint8}


def _np_view(dtype_name: str):
    return _RAW_DTYPES.get(dtype_name)


def np_from_raw(data: np.ndarray, dtype_name: str) -> np.ndarray:
    """Reinterpret a raw-word staged array back to its true dtype."""
    if dtype_name in _RAW_DTYPES:
        import ml_dtypes

        return data.view(np.dtype(getattr(ml_dtypes, dtype_name)))
    return data


class TensorEntry:
    """One shard's placement in shm and in the global array."""

    __slots__ = ("name", "dtype", "global_shape", "index", "offset",
                 "nbytes")

    def __init__(self, name: str, dtype: str,
                 global_shape: Sequence[int],
                 index: Sequence[Sequence[int]], offset: int,
                 nbytes: int):
        self.name = name
        self.dtype = dtype
        self.global_shape = tuple(global_shape)
        self.index = tuple(tuple(i) for i in index)
        self.offset = offset
        self.nbytes = nbytes

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "dtype": self.dtype,
            "global_shape": list(self.global_shape),
            "index": [list(i) for i in self.index],
            "offset": self.offset,
            "nbytes": self.nbytes,
        }

    @staticmethod
    def from_dict(d: dict) -> "TensorEntry":
        return TensorEntry(d["name"], d["dtype"], d["global_shape"],
                           d["index"], d["offset"], d["nbytes"])

    @property
    def local_shape(self) -> Tuple[int, ...]:
        return tuple(stop - start for start, stop in self.index)


def pack_meta(step: int, entries: List[TensorEntry],
              extra: Optional[dict] = None) -> bytes:
    meta = {
        "step": step,
        "extra": extra or {},
        "entries": [e.to_dict() for e in entries],
    }
    return msgpack.packb(meta, use_bin_type=True)


def unpack_meta(data: bytes) -> Tuple[int, List[TensorEntry], dict]:
    meta = msgpack.unpackb(data, raw=False, strict_map_key=False)
    entries = [TensorEntry.from_dict(d) for d in meta["entries"]]
    return meta["step"], entries, meta.get("extra", {})


def plan_entries(
    shards: List[Tuple[str, str, Sequence[int], Sequence[Sequence[int]], int]],
) -> Tuple[List[TensorEntry], int]:
    """Lay out (name, dtype, global_shape, index, nbytes) shards in shm.

    Returns entries with offsets assigned and the total payload size.
    Offsets are 128-byte aligned so persisted files mmap cleanly.
    """
    entries: List[TensorEntry] = []
    offset = 0
    for name, dtype, gshape, index, nbytes in shards:
        offset = (offset + 127) & ~127
        entries.append(TensorEntry(name, dtype, gshape, index, offset,
                                   nbytes))
        offset += nbytes
    return entries, offset


class SharedMemoryHandler:
    """Owns one shm segment for one training process's checkpoint.

    Both sides (trainer writes, agent reads) construct this with the
    same ``local_rank``; the segment is created/resized lazily on the
    writer side and attached on the reader side.
    """

    def __init__(self, local_rank: int, job: str = ""):
        import os

        job = job or os.getenv("DLROVER_TPU_JOB_NAME", "local")
        self.shm_name = f"dlrover_tpu_ckpt_{job}_{local_rank}"
        self.local_rank = local_rank
        self._shm: Optional[SharedMemoryHandle] = None
        self._lock = threading.Lock()

    # -- writer side -----------------------------------------------------

    def _ensure(self, size: int) -> SharedMemoryHandle:
        if self._shm is not None and self._shm.size >= size:
            return self._shm
        if self._shm is not None:
            self._shm.close()
            self._shm.unlink()
            self._shm = None
        # Grow with slack so step-to-step metadata jitter doesn't
        # force recreation (agent re-attaches on size change).
        self._shm = SharedMemoryHandle(self.shm_name, create=True,
                                       size=int(size * 1.1) + 4096)
        return self._shm

    def save(self, step: int,
             arrays: List[Tuple[TensorEntry, np.ndarray]],
             extra: Optional[dict] = None) -> None:
        """Write staged shards into shm. ``arrays`` pairs each planned
        entry with its host ndarray (raw view for bf16 etc.)."""
        entries = [e for e, _ in arrays]
        meta = pack_meta(step, entries, extra)
        payload = (entries[-1].offset + entries[-1].nbytes) if entries else 0
        base = _META_LEN_BYTES + len(meta)
        with self._lock:
            shm = self._ensure(base + payload)
            buf = shm.buf
            # Torn-write guard: invalidate the segment (meta_len=0)
            # before touching bytes, and publish the meta length only
            # after the full payload landed. A trainer killed mid-save
            # leaves meta_len=0 and readers see "no state" instead of a
            # silently mixed-step checkpoint.
            buf[:_META_LEN_BYTES] = (0).to_bytes(_META_LEN_BYTES,
                                                 "little")
            buf[_META_LEN_BYTES:base] = meta
            for entry, arr in arrays:
                start = base + entry.offset
                flat = np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
                buf[start:start + entry.nbytes] = flat.data
            buf[:_META_LEN_BYTES] = len(meta).to_bytes(_META_LEN_BYTES,
                                                       "little")

    # -- reader side -----------------------------------------------------

    def attach(self) -> bool:
        if self._shm is not None:
            return True
        try:
            self._shm = SharedMemoryHandle(self.shm_name)
            return True
        except FileNotFoundError:
            return False

    def load(self) -> Optional[Tuple[int, List[TensorEntry], dict, bytes]]:
        """Snapshot the segment: (step, entries, extra, payload bytes).

        The payload copy is taken under the handler lock; callers must
        additionally hold the cross-process SharedLock to exclude a
        concurrent writer.
        """
        with self._lock:
            # Always (re-)attach: the writer may have unlinked and
            # recreated a larger segment since our last look.
            if self._shm is not None:
                self._shm.close()
                self._shm = None
            if not self.attach():
                return None
            buf = self._shm.buf
            meta_len = int.from_bytes(bytes(buf[:_META_LEN_BYTES]),
                                      "little")
            if meta_len <= 0 or meta_len > len(buf):
                return None
            base = _META_LEN_BYTES + meta_len
            step, entries, extra = unpack_meta(bytes(
                buf[_META_LEN_BYTES:base]))
            payload_len = (entries[-1].offset + entries[-1].nbytes
                           if entries else 0)
            payload = bytes(buf[base:base + payload_len])
            return step, entries, extra, payload

    def no_checkpoint_state(self) -> bool:
        res = self.load()
        return res is None

    def close(self) -> None:
        with self._lock:
            if self._shm is not None:
                self._shm.close()
                self._shm = None

    def unlink(self) -> None:
        with self._lock:
            if self._shm is None:
                try:
                    self._shm = SharedMemoryHandle(self.shm_name)
                except FileNotFoundError:
                    return
            self._shm.unlink()
            self._shm.close()
            self._shm = None


def entry_array(entry: TensorEntry, payload: bytes) -> np.ndarray:
    """Materialize one entry's ndarray (raw view dtype) from payload."""
    raw = _np_view(entry.dtype)
    dtype = np.dtype(raw) if raw is not None else np.dtype(entry.dtype)
    data = np.frombuffer(payload, dtype=np.uint8,
                         count=entry.nbytes, offset=entry.offset)
    return data.view(dtype).reshape(entry.local_shape)


def assemble_global(entries: List[TensorEntry],
                    payload: bytes) -> Dict[str, np.ndarray]:
    """Reassemble {name: global ndarray (true dtype)} from shards.

    Any shard layout works — this is the reshard-on-load pivot.
    """
    out: Dict[str, np.ndarray] = {}
    by_name: Dict[str, List[TensorEntry]] = {}
    for e in entries:
        by_name.setdefault(e.name, []).append(e)
    for name, shards in by_name.items():
        gshape = shards[0].global_shape
        raw = _np_view(shards[0].dtype)
        np_dtype = (np.dtype(raw) if raw is not None
                    else np.dtype(shards[0].dtype))
        full = np.empty(gshape, np_dtype)
        for e in shards:
            sl = tuple(slice(start, stop) for start, stop in e.index)
            full[sl] = entry_array(e, payload)
        out[name] = np_from_raw(full, shards[0].dtype)
    return out
