"""Typed control-plane message schema.

The reference ships pickled dataclasses inside a 2-RPC gRPC envelope
(dlrover/python/common/grpc.py + proto/elastic_training.proto:28-31).
Pickle-over-the-wire is an RCE hazard and version-fragile, so here every
message is an explicit dataclass registered in a type registry and
serialized with msgpack: ``{"_t": <type name>, ...fields}``. Unknown
fields are dropped on decode, which gives forward/backward compatibility.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Type

import msgpack

_REGISTRY: Dict[str, type] = {}


def message(cls):
    """Class decorator: make a dataclass a wire message."""
    cls = dataclasses.dataclass(cls)
    _REGISTRY[cls.__name__] = cls
    return cls


def _encode_value(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return encode_to_dict(v)
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    return v


def encode_to_dict(msg: Any) -> dict:
    d = {"_t": type(msg).__name__}
    for f in dataclasses.fields(msg):
        d[f.name] = _encode_value(getattr(msg, f.name))
    return d


def decode_from_dict(d: Any) -> Any:
    if isinstance(d, dict) and "_t" in d:
        cls = _REGISTRY.get(d["_t"])
        if cls is None:
            raise ValueError(f"unknown message type {d['_t']!r}")
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {
            k: decode_from_dict(v)
            for k, v in d.items()
            if k != "_t" and k in fields
        }
        return cls(**kwargs)
    if isinstance(d, list):
        return [decode_from_dict(x) for x in d]
    if isinstance(d, dict):
        return {k: decode_from_dict(v) for k, v in d.items()}
    return d


def serialize(
    msg: Any,
    trace: Optional[Dict[str, str]] = None,
    job_id: Optional[str] = None,
) -> bytes:
    """Encode a message for the wire. ``trace`` (the dict
    ``obs.tracer.inject()`` produced) rides as a reserved top-level
    ``_tc`` envelope field — never a message field, so every message
    type propagates trace context without schema changes, and an old
    decoder simply drops it (``decode_from_dict`` filters unknown
    keys). ``job_id`` rides the same way as ``_job``: the multi-job
    pool master routes every message type to that job's servicer
    without any per-message schema change, and a single-job master
    (no routing dispatcher) ignores it."""
    d = encode_to_dict(msg)
    if trace:
        d["_tc"] = {str(k): str(v) for k, v in trace.items()}
    if job_id:
        d["_job"] = str(job_id)
    return msgpack.packb(d, use_bin_type=True)


def deserialize(data: bytes) -> Any:
    return decode_from_dict(
        msgpack.unpackb(data, raw=False, strict_map_key=False)
    )


def deserialize_with_trace(data: bytes):
    """``(message, trace_carrier_or_None)`` — the server-side pair of
    :func:`serialize`'s ``trace=``. The carrier is the raw ``_tc``
    dict (feed it to ``obs.tracer.extract``)."""
    msg_, trace, _ = deserialize_envelope(data)
    return msg_, trace


def deserialize_envelope(data: bytes):
    """``(message, trace_carrier_or_None, job_id)`` — the full
    server-side envelope: typed message, raw ``_tc`` trace carrier,
    and the ``_job`` routing id ("" when absent, i.e. a single-job
    client)."""
    raw = msgpack.unpackb(data, raw=False, strict_map_key=False)
    trace = None
    job_id = ""
    if isinstance(raw, dict):
        trace = raw.pop("_tc", None)
        job_id = str(raw.pop("_job", "") or "")
    return decode_from_dict(raw), trace, job_id


# ---------------------------------------------------------------------------
# Envelope
# ---------------------------------------------------------------------------


@message
class BaseRequest:
    node_id: int = -1
    node_type: str = ""
    data: Optional[Any] = None


@message
class BaseResponse:
    success: bool = True
    message: str = ""
    data: Optional[Any] = None


# ---------------------------------------------------------------------------
# Rendezvous (ref grpc.py JoinRendezvousRequest etc.)
# ---------------------------------------------------------------------------


@message
class JoinRendezvousRequest:
    node_id: int = -1
    node_rank: int = -1
    local_world_size: int = 1
    rdzv_name: str = ""
    node_ip: str = ""


@message
class JoinRendezvousResponse:
    round: int = 0


@message
class CommWorldRequest:
    node_id: int = -1
    node_rank: int = -1  # rendezvous worlds are keyed by rank, not id
    rdzv_name: str = ""


@message
class CommWorldResponse:
    rdzv_name: str = ""
    round: int = 0
    group: int = 0
    # node_rank -> local_world_size for every node frozen into this world
    world: Dict[int, int] = dataclasses.field(default_factory=dict)


@message
class WaitingNodeNumRequest:
    node_id: int = -1
    rdzv_name: str = ""


@message
class WaitingNodeNumResponse:
    waiting_num: int = 0


@message
class NetworkReadyRequest:
    node_id: int = -1


@message
class NetworkCheckResultRequest:
    node_id: int = -1
    normal: bool = True
    elapsed_time: float = 0.0


@message
class NetworkCheckQueryRequest:
    node_id: int = -1
    kind: str = "fault"  # "fault" | "straggler"


@message
class NetworkCheckQueryResponse:
    nodes: List[int] = dataclasses.field(default_factory=list)
    # "" = verdict ready; "waiting" = not all nodes reported yet;
    # "fault" = fault nodes present
    reason: str = ""


# ---------------------------------------------------------------------------
# KV store (c10d-style bootstrap over the master)
# ---------------------------------------------------------------------------


@message
class KVStoreSetRequest:
    key: str = ""
    value: bytes = b""


@message
class KVStoreGetRequest:
    key: str = ""


@message
class KVStoreGetResponse:
    found: bool = False
    value: bytes = b""


@message
class KVStoreAddRequest:
    key: str = ""
    amount: int = 0


@message
class KVStoreAddResponse:
    value: int = 0


# ---------------------------------------------------------------------------
# Dynamic data sharding (ref grpc.py TaskRequest/Task/ShardCheckpoint)
# ---------------------------------------------------------------------------


@message
class DatasetShardParams:
    batch_size: int = 0
    num_epochs: int = 1
    dataset_size: int = 0
    shuffle: bool = False
    num_minibatches_per_shard: int = 2
    dataset_name: str = ""
    task_type: str = ""
    storage_type: str = "table"
    # Streaming datasets: number of stream partitions the splitter
    # fabricates shards from (each carries its own offset/watermark).
    num_stream_partitions: int = 1


@message
class Shard:
    name: str = ""
    start: int = 0
    end: int = 0
    record_indices: List[int] = dataclasses.field(default_factory=list)
    # Stream partition this shard was fabricated from (streaming
    # datasets only; 0 for table/text shards). start/end index the
    # partition's own record space.
    partition: int = 0


@message
class TaskRequest:
    node_id: int = -1
    dataset_name: str = ""


@message
class Task:
    task_id: int = -1
    task_type: str = ""
    shard: Optional[Shard] = None


@message
class TaskResultRequest:
    node_id: int = -1
    dataset_name: str = ""
    task_id: int = -1
    success: bool = True


@message
class ShardCheckpointRequest:
    dataset_name: str = ""


@message
class ShardCheckpointResponse:
    content: str = ""  # JSON-encoded splitter + todo/doing state


@message
class RestoreShardRequest:
    dataset_name: str = ""
    content: str = ""


# ---------------------------------------------------------------------------
# Metrics / monitoring
# ---------------------------------------------------------------------------


@message
class GlobalStep:
    timestamp: float = 0.0
    step: int = 0


@message
class StepReport:
    node_id: int = -1
    timestamp: float = 0.0
    step: int = 0
    # tokens (or samples) processed since the last report, for throughput
    tokens: int = 0


@message
class ResourceStats:
    node_id: int = -1
    cpu_percent: float = 0.0
    memory_mb: int = 0
    hbm_used_gb: float = 0.0
    duty_cycle: float = 0.0


@message
class MetricsSnapshotReport:
    """Agent -> master: one host's telemetry snapshot, shipped on the
    ResourceMonitor cadence. ``registry`` is the host process's
    ``MetricsRegistry.dump()``; ``step_times`` are the trainer's most
    recent per-step wall times (from the step-metrics file);
    ``events`` are tracer events new since the previous snapshot (how
    trainer-side spans reach the master's goodput accountant). The
    master's FleetAggregator merges these into host-labeled series.
    ``beacon`` is the trainer's last progress stamp (obs/beacon.py
    record plus the agent-computed ``age_s`` staleness) — the
    StallCorrelator's per-host progress vector; empty when the host
    runs no beacon.
    """

    node_id: int = -1
    host: str = ""
    timestamp: float = 0.0
    registry: Dict[str, Any] = dataclasses.field(default_factory=dict)
    resource: Dict[str, float] = dataclasses.field(default_factory=dict)
    step_times: List[float] = dataclasses.field(default_factory=list)
    events: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    beacon: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class NodeFailureReport:
    node_id: int = -1
    error_data: str = ""
    level: str = ""
    restart_count: int = 0
    # True when the reporting agent has exhausted its local restart
    # budget: the node is done, do not relaunch.
    fatal: bool = False
    # Size-capped forensics digest (bundle path + top stack frames +
    # last recorder events) attached by the agent on hangs/crashes.
    # Deliberately separate from error_data: the exit classifier must
    # key on the raw stderr only, never on stack-frame file names.
    diagnostics: str = ""


@message
class NodeSucceededReport:
    node_id: int = -1


@message
class DiagnosticsReport:
    """Agent -> master: one forensics digest (hang, crash, or an
    on-demand ``diagnose`` snapshot). ``bundle_path`` points at the
    full JSON black-box bundle on the reporting host's forensics dir;
    ``digest`` is the size-capped summary (top stack frames, last
    notes/log lines) safe to keep in master memory and render over
    RPC. The master keeps a bounded per-node history
    (``DiagnosticsQueryRequest``)."""

    node_id: int = -1
    kind: str = ""  # "hang" | "crash" | "diagnose" | ...
    bundle_path: str = ""
    digest: str = ""
    timestamp: float = 0.0


@message
class ProfileActionRequest:
    """Operator/tool -> master: queue a PROFILE heartbeat action for
    ``node_id`` (its agent asks the trainer for an N-step phase/MFU
    capture; the digest lands in the diagnostics history, queryable
    via ``DiagnosticsQueryRequest``). The capture length is the
    agent's ``DLROVER_TPU_PROFILE_STEPS``."""

    node_id: int = -1


@message
class DiagnosticsQueryRequest:
    """Fetch the master's per-node diagnostics history; ``node_id``
    -1 means every node."""

    node_id: int = -1


@message
class DiagnosticsQueryResponse:
    reports: List[DiagnosticsReport] = dataclasses.field(
        default_factory=list
    )


@message
class HealthVerdictMsg:
    """One health-detector finding on the wire (the RPC mirror of
    ``obs.health.HealthVerdict``). ``evidence`` is the convicting
    window of ``[ts, value]`` samples; ``metrics`` the detector's
    numeric facts (baseline mean, ratio, slope, ...)."""

    detector: str = ""
    severity: str = ""  # "info" | "warn" | "critical"
    message: str = ""
    node_id: int = -1
    host: str = ""
    suggested_action: str = ""  # an EventAction value, or ""
    evidence_series: str = ""
    evidence: List[List[float]] = dataclasses.field(
        default_factory=list
    )
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    timestamp: float = 0.0
    resolved: bool = False


@message
class HealthQueryRequest:
    """Fetch the master's health verdicts. ``node_id`` >= 0 filters
    to one node's verdicts; ``include_history`` adds the bounded
    transition history (new verdicts, severity changes, resolutions)
    to the response."""

    node_id: int = -1
    include_history: bool = False


@message
class HealthQueryResponse:
    score: float = 1.0
    verdicts: List[HealthVerdictMsg] = dataclasses.field(
        default_factory=list
    )
    history: List[HealthVerdictMsg] = dataclasses.field(
        default_factory=list
    )


@message
class RemediationDecisionMsg:
    """One remediation-engine decision on the wire (the RPC mirror of
    ``master.remediation.RemediationDecision``). ``governors`` maps
    every safety-governor name to ``"ok"`` or a ``"blocked: ..."``
    reason; ``trigger`` is the convicting verdict's message."""

    decision_id: int = 0
    detector: str = ""
    severity: str = ""
    node_id: int = -1
    host: str = ""
    action: str = ""  # restart_training | cordon_replace | shrink
    outcome: str = ""  # acted | dry_run | blocked | recovered | ...
    dry_run: bool = False
    governors: Dict[str, str] = dataclasses.field(default_factory=dict)
    trigger: str = ""
    timestamp: float = 0.0
    probation_deadline: float = 0.0
    note: str = ""
    # The decision's distributed trace (verdict -> governors ->
    # action -> probation -> outcome spans), queryable via
    # TraceQueryRequest.
    trace_id: str = ""


@message
class RemediationQueryRequest:
    """Fetch the master's remediation decision history. ``node_id``
    >= 0 filters to one node's decisions; ``limit`` > 0 caps the
    newest-last decision list."""

    node_id: int = -1
    limit: int = 0


@message
class RemediationQueryResponse:
    enabled: bool = False
    dry_run: bool = False
    cordoned: List[int] = dataclasses.field(default_factory=list)
    probation_failing: bool = False
    decisions: List[RemediationDecisionMsg] = dataclasses.field(
        default_factory=list
    )


@message
class NodeFailureResponse:
    # A NodeAction constant: who owns the restart after this failure.
    action: str = "restart_in_place"


@message
class HeartbeatRequest:
    node_id: int = -1
    timestamp: float = 0.0


@message
class HeartbeatResponse:
    action: str = "none"  # an EventAction value pushed down by the master


@message
class NodeAddressRequest:
    node_id: int = -1
    node_type: str = ""
    node_ip: str = ""
    # Role labels for the node-table entry (e.g. a serving replica's
    # {"serving_role": "prefill"|"decode"|"mixed"}): the labeled
    # ensure_role seam counts targets per label set, so per-role
    # autoscaling can launch/count each role independently. An old
    # decoder simply drops the field.
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@message
class ParallelConfigRequest:
    node_id: int = -1


@message
class ParallelConfig:
    """Master-pushed tuning config (ref grpc.ParallelConfig).

    On TPU the tunables are the mesh shape and per-step batching, not
    DDP bucket sizes.
    """

    mesh_shape: Dict[str, int] = dataclasses.field(default_factory=dict)
    micro_batch_size: int = 0
    grad_accum_steps: int = 0
    remat_policy: str = ""
    version: int = 0


# ---------------------------------------------------------------------------
# Elasticity / scaling
# ---------------------------------------------------------------------------


@message
class JobNodesRequest:
    node_type: str = ""


@message
class NodeMeta:
    node_type: str = ""
    node_id: int = -1
    rank: int = -1
    status: str = ""
    addr: str = ""
    chips: int = 0


@message
class JobNodesResponse:
    nodes: List[NodeMeta] = dataclasses.field(default_factory=list)


@message
class MetricsRequest:
    """Fetch the master's metrics in Prometheus text format over the
    control plane (same payload as the HTTP /metrics endpoint, for
    agents/tools that already hold an RPC channel)."""

    node_id: int = -1


@message
class MetricsResponse:
    text: str = ""


@message
class ScalePlanMsg:
    """A resource plan: target number of nodes per type."""

    node_group: Dict[str, int] = dataclasses.field(default_factory=dict)
    remove_nodes: List[int] = dataclasses.field(default_factory=list)


# ---------------------------------------------------------------------------
# Sparse / PS-elastic path (ref: tfplus kv_variable ops + dlrover
# master/node/ps.py orchestration; arrays ride msgpack as raw bytes)
# ---------------------------------------------------------------------------


@message
class Tensor:
    """Dense ndarray on the wire: raw bytes + dtype + shape."""

    dtype: str = "float32"
    shape: List[int] = dataclasses.field(default_factory=list)
    data: bytes = b""

    @staticmethod
    def from_numpy(arr) -> "Tensor":
        import numpy as np

        arr = np.ascontiguousarray(arr)
        return Tensor(
            dtype=str(arr.dtype),
            shape=list(arr.shape),
            data=arr.tobytes(),
        )

    def to_numpy(self):
        import numpy as np

        return np.frombuffer(self.data, dtype=self.dtype).reshape(
            self.shape
        ).copy()


@message
class PsLookupRequest:
    table: str = ""
    keys: Optional[Tensor] = None
    train: bool = True  # True: gather-or-insert; False: gather-or-zeros
    map_version: int = -1


@message
class PsLookupResponse:
    values: Optional[Tensor] = None


@message
class PsApplyRequest:
    """Fused sparse optimizer apply on a PS shard."""

    table: str = ""
    optimizer: str = "adam"
    keys: Optional[Tensor] = None
    grads: Optional[Tensor] = None
    # Optional per-key auxiliary rows, same [n, dim] layout as grads
    # (adahessian: the Hutchinson Hessian-diagonal estimates).
    aux: Optional[Tensor] = None
    step: int = 0
    lr: float = 1e-3
    hyperparams: Dict[str, float] = dataclasses.field(default_factory=dict)
    map_version: int = -1
    # Replay fence (exactly-once streaming): barrier epoch the client
    # is applying under, its stable client id, and a per-client
    # monotonically increasing apply sequence. A post-restore PS
    # rejects epochs older than its fence and dedups replayed
    # (client_id, apply_seq) pairs per partition, so a trainer
    # replaying its in-flight shard after a kill is idempotent.
    # All three default to -1 = unfenced (legacy at-least-once path).
    epoch: int = -1
    client_id: int = -1
    apply_seq: int = -1


@message
class PsExportRequest:
    """Export rows of the given partitions (for PS->PS moves and for
    checkpoint flush). since_version>0 = delta export."""

    table: str = ""
    partitions: List[int] = dataclasses.field(default_factory=list)
    since_version: int = 0
    include_slots: bool = True


@message
class PsTableDump:
    table: str = ""
    keys: Optional[Tensor] = None
    values: Optional[Tensor] = None
    freqs: Optional[Tensor] = None
    versions: Optional[Tensor] = None
    # slot name -> (keys, values) for optimizer state
    slot_keys: Dict[str, Tensor] = dataclasses.field(default_factory=dict)
    slot_values: Dict[str, Tensor] = dataclasses.field(default_factory=dict)
    # Replay-fence state for the dumped partitions: partition ->
    # {client_id: last applied seq}, plus the source's fence epoch.
    # Rides PS-to-PS moves so a rebalanced partition keeps its dedup
    # history (without it a live move would reopen the replay window).
    part_seqs: Dict[int, Dict[int, int]] = dataclasses.field(
        default_factory=dict
    )
    fence_epoch: int = -1


@message
class PsImportRequest:
    dump: Optional[PsTableDump] = None


@message
class PsPullPartitionsRequest:
    """Master -> target PS: pull these partitions from source_addr,
    import them, ack. The data moves PS-to-PS, not through the master."""

    source_addr: str = ""
    partitions: List[int] = dataclasses.field(default_factory=list)


@message
class PsFreezeRequest:
    """Master -> source PS: stop serving these partitions (clients get
    a stale-map rejection and refetch the PartitionMap)."""

    partitions: List[int] = dataclasses.field(default_factory=list)
    frozen: bool = True


@message
class PsStatsRequest:
    pass


@message
class PsStatsResponse:
    ps_id: int = -1
    tables: Dict[str, int] = dataclasses.field(default_factory=dict)
    qps: float = 0.0
    cpu_percent: float = 0.0
    frozen_partitions: List[int] = dataclasses.field(default_factory=list)


@message
class PsFlushRequest:
    """Checkpoint: delta-flush owned partitions to storage.

    A barrier flush (``epoch >= 0``) additionally persists the replay
    fence (per-partition applied-seq high water marks) stamped with
    the shard ledger's high-water marks, and advances the PS fence
    epoch — the PS half of a barrier-consistent checkpoint cut.
    """

    step: int = 0
    epoch: int = -1
    # Shard-ledger high-water marks at the cut: dataset -> watermark
    # record offset (forensics stamp carried into the fence files).
    hwm: Dict[str, int] = dataclasses.field(default_factory=dict)


@message
class PsFlushResponse:
    flushed_rows: int = 0
    # Fence epoch in force on the PS after this flush (-1 = no
    # barrier flush has ever run there).
    epoch: int = -1


@message
class PsRestoreRequest:
    """Restore the given partitions from the checkpoint dir (after a
    relaunch or a partition takeover from a dead PS)."""

    partitions: List[int] = dataclasses.field(default_factory=list)


@message
class PartitionMapMsg:
    version: int = 0
    assignment: List[int] = dataclasses.field(default_factory=list)
    ps_addrs: Dict[int, str] = dataclasses.field(default_factory=dict)


@message
class PartitionMapRequest:
    known_version: int = -1


@message
class PsRegisterRequest:
    """PS node -> master: announce service address."""

    node_id: int = -1
    addr: str = ""


@message
class PsStatsReport:
    """PS node -> master: periodic telemetry for the hot-PS optimizer."""

    node_id: int = -1
    qps: float = 0.0
    cpu_percent: float = 0.0
    total_rows: int = 0


@message
class PsSetPartitionsRequest:
    """Master -> PS: own these partitions at this map version."""

    partitions: List[int] = dataclasses.field(default_factory=list)
    map_version: int = 0


@message
class StreamBarrierRequest:
    """Trainer -> master: cut a barrier-consistent checkpoint of the
    streaming sparse path (Chandy-Lamport style: the trainer has
    quiesced its in-flight applies before sending this). The master
    flushes every PS partition stamped with the shard ledger's
    high-water marks, then durably journals (epoch, offsets,
    watermarks, flush generation) as one atomic snapshot before
    acking. ``epoch`` < 0 asks the master to assign the next epoch."""

    dataset_name: str = ""
    epoch: int = -1
    step: int = 0


@message
class StreamBarrierResponse:
    """The durable barrier record (also the answer to a
    StreamBarrierQueryRequest; ``epoch`` < 0 = no barrier yet)."""

    dataset_name: str = ""
    epoch: int = -1
    step: int = 0
    # Per-stream-partition fabrication offsets at the cut.
    offsets: Dict[int, int] = dataclasses.field(default_factory=dict)
    # Per-stream-partition completed-record watermarks at the cut.
    watermarks: Dict[int, int] = dataclasses.field(default_factory=dict)
    # Master state-store generation the record became durable in.
    flush_gen: int = 0
    flushed_rows: int = 0
    durable: bool = False


@message
class StreamBarrierQueryRequest:
    """Trainer -> master: the last durable barrier for a dataset
    (resume point after a trainer restart)."""

    dataset_name: str = ""


# ---------------------------------------------------------------------------
# Serving plane (dlrover_tpu/serving/): clients submit generation
# requests to the master's router; replicas PULL work and REPORT
# completions/stats, mirroring the task-manager shard protocol so the
# same requeue-on-death semantics apply to requests.
# ---------------------------------------------------------------------------


@message
class ServeSubmitRequest:
    """Client -> master: one generation request. ``request_id`` is an
    optional caller idempotence token (resubmitting a known id
    returns it unchanged instead of double-queueing)."""

    prompt: List[int] = dataclasses.field(default_factory=list)
    max_new_tokens: int = 16
    temperature: float = 0.0
    request_id: str = ""


@message
class ServeSubmitResponse:
    request_id: str = ""
    accepted: bool = True
    # The distributed trace minted (or adopted) for this request at
    # the router — feed it to query_traces for the causal timeline.
    trace_id: str = ""


@message
class ServeWorkItem:
    """One dispatched request on the wire (router -> replica).
    ``trace`` is the request's trace context (an
    ``obs.tracer.inject()`` carrier): the replica re-attaches it so
    scheduler events on any hop — including every requeue hop —
    stay in one causal timeline."""

    request_id: str = ""
    prompt: List[int] = dataclasses.field(default_factory=list)
    max_new_tokens: int = 16
    temperature: float = 0.0
    trace: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Prefill/decode disaggregation: a packed HandoffPayload wire
    # dict (serving/handoff.py — raw KV bytes + dtype/shape, msgpack-
    # safe) when this item is a completed prefill bound for a
    # decode-role replica; empty for raw prompts.
    handoff: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class ServePullRequest:
    """Replica -> master: give me up to ``max_items`` requests. Only
    READY replicas are fed; the pull counts as liveness progress."""

    replica_id: int = -1
    max_items: int = 1


@message
class ServePullResponse:
    items: List[ServeWorkItem] = dataclasses.field(
        default_factory=list
    )


@message
class ServeCompletedReport:
    """Replica -> master: a request finished (or failed when
    ``error`` is non-empty). First completion wins in the router's
    ledger; late duplicates after a requeue are dropped."""

    replica_id: int = -1
    request_id: str = ""
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: float = 0.0
    tpot_s: float = 0.0
    finish_reason: str = ""
    error: str = ""
    # Replica-side TTFT decomposition, per-phase durations in seconds
    # (dispatch = scheduler queue wait, prefill, first_decode, decode,
    # and "handoff" — the decode replica's import wait — on
    # disaggregated completions) — the master folds these into the
    # request's trace timeline and the
    # dlrover_serve_ttft_phase_seconds histograms.
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    # Prefill/decode disaggregation: a prefill-role replica reports a
    # finished PROMPT here — the packed KV HandoffPayload rides this
    # field and the report is a stage transition (queued for a decode
    # replica), not a completion.
    handoff: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class ServeResultRequest:
    request_id: str = ""


@message
class ServeResultResponse:
    """The router ledger's view of one request. ``state`` is
    queued | dispatched | done | failed (empty = unknown id)."""

    request_id: str = ""
    state: str = ""
    replica_id: int = -1
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: str = ""
    finish_reason: str = ""
    requeues: int = 0
    ttft_s: float = 0.0
    tpot_s: float = 0.0
    latency_s: float = 0.0
    trace_id: str = ""
    # Master-assembled TTFT decomposition: queue (router) + the
    # replica-reported phases of the completing hop.
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)


@message
class ServeStatsReport:
    """Replica -> master: periodic scheduler telemetry (the
    ``ContinuousBatchingScheduler.stats()`` dict: queue depth, active
    sequences, KV pool snapshot, TTFT/TPOT percentiles, token
    counters). The router treats a moving token counter as serving
    progress for the replica_unhealthy watchdog."""

    replica_id: int = -1
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class ServeQueryRequest:
    """Fetch the router's FULL serving snapshot (per-replica
    health/stats, request counters, QPS/p99) — the obs_report
    --serving feed. Deliberately fieldless: there is no per-node
    filter, and a dead field would advertise one."""

    pass


@message
class ServeQueryResponse:
    enabled: bool = False
    snapshot: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class TraceQueryRequest:
    """Fetch assembled trace timelines from the master's trace store.
    ``trace_id`` wins when given; else ``subject`` filters by
    membership (a serving request id, or ``node:<id>``); else every
    retained trace. ``limit`` > 0 keeps the newest N."""

    trace_id: str = ""
    subject: str = ""
    limit: int = 0


@message
class TraceQueryResponse:
    """``traces`` are trace-store timelines: ``{trace_id, start_ts,
    end_ts, subjects, spans: [{name, span_id, parent_span_id,
    start_ts, dur_s, tags}], dropped_spans}``, newest last."""

    enabled: bool = False
    traces: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )


# ---------------------------------------------------------------------------
# Multi-job pool control plane (dlrover_tpu/pool/): clients submit
# jobs to the pool master's gang scheduler; every per-job RPC above
# rides the same envelope with its ``_job`` id, so these messages are
# only the POOL-level surface (submit/status/snapshot).
# ---------------------------------------------------------------------------


@message
class PoolSubmitRequest:
    """Client/operator -> pool master: queue one job. ``priority`` is
    an integer band (higher wins; bounded 0..9 by the scheduler);
    ``n_slices`` the gang size (placed whole or not at all);
    ``min_slices`` > 0 the elastic floor a PREEMPTED job may resume
    with when full capacity has not returned yet. Resubmitting a
    known ``job_id`` is idempotent (returns its current state)."""

    job_id: str = ""
    tenant: str = "default"
    priority: int = 0
    n_slices: int = 1
    min_slices: int = 0
    queue: str = "default"


@message
class PoolSubmitResponse:
    job_id: str = ""
    accepted: bool = True
    state: str = ""  # a PoolJobState value
    reason: str = ""  # e.g. "quota: tenant over cap" when queued
    # The job's pool-lifecycle distributed trace (submit -> queue ->
    # place -> [preempt -> resume]* -> complete) — feed query_traces.
    trace_id: str = ""


@message
class PoolJobStatusRequest:
    job_id: str = ""


@message
class PoolJobStatusResponse:
    job_id: str = ""
    known: bool = False
    state: str = ""
    tenant: str = ""
    priority: int = 0
    n_slices: int = 0
    slices: List[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    trace_id: str = ""
    message: str = ""


@message
class PoolQueryRequest:
    """Fetch the pool scheduler's FULL snapshot (queue depth per
    priority band, per-tenant quota usage, slice utilization,
    preemption counts, wait-time percentiles) — the
    ``obs_report --pool`` feed. Deliberately fieldless, like
    ServeQueryRequest."""

    pass


@message
class PoolQueryResponse:
    enabled: bool = False
    snapshot: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class CapacityQueryRequest:
    """Fetch the pool master's capacity accounting rollup: per-tenant
    chip-second totals by slice state, goodput-per-chip, and the SLO
    error-budget standing (budget remaining + active burn alerts) —
    the ``obs_report --capacity`` feed. Fieldless, like
    PoolQueryRequest."""

    pass


@message
class CapacityQueryResponse:
    enabled: bool = False
    # CapacityLedger.snapshot() with an "slo" block
    # ({"budgets": HealthMonitor.slo_snapshot()}) attached.
    snapshot: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class StallQueryRequest:
    """Fetch the master's stall-localization snapshot: the per-host
    progress table (last beacon step/phase/age), any open or recent
    ``collective_stall`` incident with its localized culprit, trace
    id, and coordinated-capture bundle paths — the
    ``obs_report --stall`` feed. Fieldless, like CapacityQueryRequest."""

    pass


@message
class StallQueryResponse:
    enabled: bool = False
    # StallCorrelator.snapshot(): {"hosts": {host: {...progress...}},
    # "incident": {...} | {}, "incidents": [...], "config": {...}}.
    snapshot: Dict[str, Any] = dataclasses.field(default_factory=dict)


# -- brain service wire messages (standalone brain: brain/server.py) --


@message
class BrainPersistRequest:
    """Master/agents -> brain: persist one record. ``kind`` selects
    the table ("metrics" | "sample" | "ps_job"); ``payload`` carries
    the record's fields (JobMetricsRecord / RuntimeSample /
    persist_ps_job kwargs)."""

    kind: str = ""
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class BrainOptimizeRequest:
    """Master -> brain: run a registered algorithm (the reference's
    brain.Optimize RPC with its ProcessorID dispatch,
    go/brain/pkg/optimizer/...). ``args``/``kwargs`` feed the
    algorithm's positional/keyword parameters after the service."""

    algorithm: str = ""
    args: List[Any] = dataclasses.field(default_factory=list)
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)


@message
class BrainOptimizeResponse:
    ok: bool = True
    # Algorithm result, JSON-ish (None / number / dict / list).
    result: Any = None
    error: str = ""


# ---------------------------------------------------------------------------
# Cross-pod data ingest (ref: atorch coworker pods feeding training
# pods over RPC, atorch/data/coworker_dataset.py:16,25-40 +
# shm_context.py — there torch rpc, here the typed msgpack layer)
# ---------------------------------------------------------------------------


@message
class DataBatchPush:
    """Remote coworker pod -> training host: one preprocessed batch.

    The training host's BatchIngestServer (data/ingest.py) copies the
    arrays into its local shm ring; the reply is a DataBatchAck whose
    ``accepted=False`` is backpressure (ring full) — the pod retries
    after a backoff instead of overrunning the consumer."""

    pod_id: int = 0
    seq: int = 0
    arrays: Dict[str, Tensor] = dataclasses.field(default_factory=dict)


@message
class DataBatchAck:
    accepted: bool = True


@message
class DataStreamEnd:
    """Remote pod -> training host: this pod's stream is over (or
    failed, when ``error`` is non-empty)."""

    pod_id: int = 0
    produced: int = 0
    error: str = ""
