"""Framework-wide constants and enums.

Capability parity with the reference's constant registry
(dlrover/python/common/constants.py), redesigned for a TPU fleet: node
types are host/master rather than PS/worker-GPU, and accelerator metadata
speaks TPU topologies (chips per host, ICI slice shape) instead of GPUs.
"""

from __future__ import annotations

import enum


class NodeType:
    """Roles a node can play in a job."""

    MASTER = "master"
    # A TPU host (one VM of a pod slice, owning N chips).
    WORKER = "worker"
    # The coordinating worker (rank-0 duties: variable init in PS
    # strategy, checkpoint commits). Critical by default.
    CHIEF = "chief"
    # CPU-only preprocessing host (coworker architecture).
    DATA_WORKER = "data_worker"
    # Parameter-server-style host for the sparse embedding path.
    EMBEDDING = "embedding"
    # Inference replica in the serving plane (dlrover_tpu/serving/):
    # hosts a model copy behind a continuous-batching scheduler,
    # registered in the same node table as training roles but outside
    # the training rendezvous and speed accounting.
    REPLICA = "replica"
    EVALUATOR = "evaluator"

    ALL = (MASTER, WORKER, DATA_WORKER, EMBEDDING, EVALUATOR)


# PS (EMBEDDING) hosts pick their own ps_id starting at 0, same as
# workers pick ranks — the job-manager node table is shared, so PS
# node ids live in their own namespace to avoid colliding with (and
# silently merging onto) worker nodes of the same id.
PS_NODE_ID_BASE = 1_000_000


def ps_node_id(ps_id: int) -> int:
    return PS_NODE_ID_BASE + ps_id


def node_ps_id(node_id: int) -> int:
    return node_id - PS_NODE_ID_BASE


# Evaluator ids are namespaced the same way PS ids are: an evaluator
# launched with the default rank 0 must never merge onto worker 0's
# node-table entry (the agent uses its node_id for register/heartbeat/
# failure RPCs, so the namespacing happens at the agent).
EVALUATOR_NODE_ID_BASE = 2_000_000


def evaluator_node_id(index: int) -> int:
    return EVALUATOR_NODE_ID_BASE + index


# Coworker (DATA_WORKER) pods likewise: their pod ids start at 0 and
# must not merge onto worker/PS/evaluator node-table entries.
DATA_WORKER_NODE_ID_BASE = 3_000_000


def data_worker_node_id(pod_id: int) -> int:
    return DATA_WORKER_NODE_ID_BASE + pod_id


# Serving replicas likewise: replica 0 must never merge onto worker
# 0's node-table entry (the replica worker namespaces its id before
# register/heartbeat RPCs, serving/replica.py).
REPLICA_NODE_ID_BASE = 4_000_000


def replica_node_id(replica_id: int) -> int:
    return REPLICA_NODE_ID_BASE + replica_id


class NodeStatus:
    """Lifecycle states of a node; transitions in common/status_flow.py."""

    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    BREAKDOWN = "breakdown"  # hardware failure detected by health check

    ALIVE = (PENDING, RUNNING)
    TERMINAL = (SUCCEEDED, FAILED, DELETED, BREAKDOWN)


class NodeEventType:
    CREATED = "created"
    MODIFIED = "modified"
    DELETED = "deleted"


class NodeAction:
    """Master's verdict on a failure report: who owns the restart."""

    RESTART_IN_PLACE = "restart_in_place"  # agent respawns the process
    RELAUNCH_NODE = "relaunch_node"  # master replaces the node (pod)
    STOP = "stop"  # no restart at all


class NodeExitReason:
    """Why a node's training process exited; drives relaunch policy."""

    SUCCEEDED = "succeeded"
    KILLED = "killed"
    OOM = "oom"
    FATAL_ERROR = "fatal_error"
    HARDWARE_ERROR = "hardware_error"
    PREEMPTED = "preempted"
    UNKNOWN = "unknown"

    # Exit reasons that should never be relaunched.
    NO_RELAUNCH = (SUCCEEDED, FATAL_ERROR)


class JobStage:
    INIT = "init"
    RENDEZVOUS = "rendezvous"
    TRAINING = "training"
    SUSPENDED = "suspended"
    SUCCEEDED = "succeeded"
    FAILED = "failed"


class RendezvousName:
    TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class TaskType:
    """Dynamic-sharding task types handed to workers."""

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"
    NONE = "none"


class DatasetType:
    TABLE = "table"
    TEXT = "text"
    STREAMING = "streaming"


class TrainingExceptionLevel:
    PROCESS_ERROR = "process_error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    WARNING = "warning"
    INFO = "info"


class PlatformType:
    LOCAL = "local"
    KUBERNETES = "k8s"
    RAY = "ray"


class Accelerators:
    TPU = "tpu"
    CPU = "cpu"  # for tests / virtual meshes


class TpuGeneration:
    V4 = "v4"
    V5E = "v5e"
    V5P = "v5p"
    V6E = "v6e"

    # Peak bf16 matmul TFLOP/s per chip, used by the analyser's cost model.
    PEAK_BF16_TFLOPS = {V4: 275.0, V5E: 197.0, V5P: 459.0, V6E: 918.0}
    # HBM bytes/s per chip.
    HBM_GBPS = {V4: 1228.0, V5E: 819.0, V5P: 2765.0, V6E: 1640.0}


class CheckpointConstant:
    TRACKER_FILE = "latest_checkpointed_iteration.txt"
    STEP_DIR_PREFIX = "iter_"
    DONE_FILE_PREFIX = "done_"
    MODEL_STATE_NAME = "model_state"
    OPTIM_STATE_NAME = "optim_state"
    EXTRA_STATE_NAME = "extra_state"


class NodeEnv:
    """Environment variables understood by agents and training processes."""

    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    # Multi-job pool routing id: when set, every master RPC this
    # process makes carries it on the envelope's _job field so the
    # pool master routes to this job's servicer. Unset/empty =
    # single-job mode (unchanged behavior).
    POOL_JOB_ID = "DLROVER_TPU_POOL_JOB_ID"
    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    LOCAL_WORLD_SIZE = "DLROVER_TPU_LOCAL_WORLD_SIZE"
    # JAX distributed bootstrap (coordinator = rank-0 host).
    COORDINATOR_ADDR = "DLROVER_TPU_COORDINATOR_ADDR"
    PROCESS_ID = "DLROVER_TPU_PROCESS_ID"
    NUM_PROCESSES = "DLROVER_TPU_NUM_PROCESSES"
    # Restart bookkeeping
    RESTART_COUNT = "DLROVER_TPU_RESTART_COUNT"
    # Platform type: local | k8s | ray
    PLATFORM = "DLROVER_TPU_PLATFORM"
    # Monitoring
    MONITOR_ENABLED = "DLROVER_TPU_MONITOR_ENABLED"


class GrpcEnv:
    MAX_MESSAGE_LENGTH = 256 * 1024 * 1024


class DefaultValues:
    RDZV_TIMEOUT_SECS = 600
    PENDING_TIMEOUT_SECS = 900
    HANG_TIMEOUT_SECS = 1800
    SHARD_TIMEOUT_SECS = 300
    RELAUNCH_MAX = 3
    MASTER_PORT = 0  # 0 = pick a free port
    SAVE_MEM_INTERVAL_SECS = 30
    REPORT_INTERVAL_SECS = 15


class JobExitReason:
    SUCCEEDED = "succeeded"
    NODE_OOM = "node_oom_error"
    NODE_FATAL = "node_fatal_error"
    RDZV_TIMEOUT = "rendezvous_timeout"
    PENDING_TIMEOUT = "pending_timeout"
    # A critical node (chief/evaluator/critical worker/PS) exhausted
    # its relaunch budget: the job cannot make progress without it.
    CRITICAL_NODE_FAILED = "critical_node_failed"
    UNKNOWN = "unknown"


class ErrorMonitorConstants:
    TYPE_INFO = "info"
    TYPE_ERROR = "error"
    ACTION_RELAUNCH = "relaunch"
    ACTION_STOP = "stop"


class EventAction(str, enum.Enum):
    """Actions the master can push down to agents."""

    NONE = "none"
    RESTART_TRAINING = "restart_training"
    STOP_TRAINING = "stop_training"
    SAVE_CHECKPOINT = "save_checkpoint"
    # Take an on-demand forensics snapshot: the agent SIGUSR1s its
    # training process for a stack dump, writes its own recorder
    # bundle, and ships a DiagnosticsReport back to the master.
    DIAGNOSE = "diagnose"
    # Capture an on-demand N-step performance profile: the agent
    # drops a request file for its trainer's step-phase profiler,
    # waits for the phase/MFU digest, and ships it back as a
    # DiagnosticsReport(kind="profile").
    PROFILE = "profile"
    # Remediation engine: the agent stops its training process and
    # sits OUT of rendezvous (still heartbeating) while the master
    # replaces or observes the host. A subsequent RESTART_TRAINING
    # un-cordons it (the rollback path).
    CORDON = "cordon"
