"""Cross-process IPC primitives shared by trainer and agent processes.

Capability parity with the reference's shared primitives
(dlrover/python/common/multi_process.py:211,332,439,519 — SharedLock,
SharedQueue, SharedDict over a unix-domain-socket server, plus a
SharedMemory wrapper that tolerates unlink races).

Design: one process (the *master* side, normally the host agent) serves
each primitive on an abstract unix socket derived from its name; other
processes connect as clients. Requests/replies are msgpack maps — no
pickle. The flash-checkpoint path depends on these: the trainer holds
``SharedLock`` while writing tensors into POSIX shm and posts save events
on a ``SharedQueue`` that the agent's async saver drains.
"""

from __future__ import annotations

import os
import socket
import socketserver
import threading
import time
import queue as _queue
from multiprocessing import shared_memory
from typing import Any, Dict, Optional

import msgpack

from dlrover_tpu.common.log import get_logger

logger = get_logger("ipc")

SOCKET_DIR = os.getenv("DLROVER_TPU_SOCK_DIR", "/tmp/dlrover_tpu_sock")


def _socket_path(name: str) -> str:
    os.makedirs(SOCKET_DIR, exist_ok=True)
    job = os.getenv("DLROVER_TPU_JOB_NAME", "local")
    return os.path.join(SOCKET_DIR, f"{job}_{name}.sock")


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(len(data).to_bytes(4, "big") + data)


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        if not chunk:
            return None
        header += chunk
    size = int.from_bytes(header, "big")
    data = b""
    while len(data) < size:
        chunk = sock.recv(min(65536, size - len(data)))
        if not chunk:
            return None
        data += chunk
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


class _PrimitiveServer:
    """Unix-socket request server for one named primitive."""

    def __init__(self, name: str):
        self.name = name
        self.path = _socket_path(name)
        if os.path.exists(self.path):
            os.unlink(self.path)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                conn_id = f"conn_{id(self.request)}_{threading.get_ident()}"
                try:
                    while True:
                        try:
                            req = _recv_msg(self.request)
                        except OSError:
                            return
                        if req is None:
                            return
                        req["_conn"] = conn_id
                        try:
                            resp = outer.handle_request(req)
                        except Exception as e:  # noqa: BLE001
                            resp = {"ok": False, "err": str(e)}
                        try:
                            _send_msg(self.request, resp)
                        except OSError:
                            return
                finally:
                    outer.on_disconnect(conn_id)

        class Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server(self.path, Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"ipc-{name}",
            daemon=True,
        )
        self._thread.start()

    def handle_request(self, req: dict) -> dict:  # overridden
        raise NotImplementedError

    def on_disconnect(self, conn_id: str) -> None:
        """Called when a client connection closes (incl. process death)."""

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if os.path.exists(self.path):
            os.unlink(self.path)


class _PrimitiveClient:
    """Reconnecting client to a primitive server."""

    def __init__(self, name: str, timeout: float = 60.0):
        self.name = name
        self.path = _socket_path(name)
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        deadline = time.time() + self.timeout
        while True:
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(self.path)
                self._sock = s
                return s
            except (FileNotFoundError, ConnectionRefusedError):
                if time.time() > deadline:
                    raise TimeoutError(
                        f"primitive server {self.name} not up at {self.path}"
                    )
                time.sleep(0.1)

    def call(self, req: dict) -> dict:
        with self._lock:
            for attempt in (0, 1):
                sock = self._connect()
                try:
                    _send_msg(sock, req)
                    resp = _recv_msg(sock)
                    if resp is None:
                        raise ConnectionError("server closed connection")
                    return resp
                except (ConnectionError, BrokenPipeError, OSError):
                    self._sock = None
                    if attempt == 1:
                        raise
            raise ConnectionError("unreachable")

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


# ---------------------------------------------------------------------------
# SharedLock
# ---------------------------------------------------------------------------


class _LockServer(_PrimitiveServer):
    def __init__(self, name: str):
        self._locked_by: Optional[str] = None
        self._locked_conn: Optional[str] = None
        self._cond = threading.Condition()
        super().__init__(name)

    def handle_request(self, req: dict) -> dict:
        op = req["op"]
        owner = req.get("owner", "")
        conn = req.get("_conn", "")
        if op == "acquire":
            blocking = req.get("blocking", True)
            with self._cond:
                if blocking:
                    ok = self._cond.wait_for(
                        lambda: self._locked_by is None, timeout=60.0
                    )
                    if not ok:
                        return {"ok": True, "acquired": False}
                elif self._locked_by is not None:
                    return {"ok": True, "acquired": False}
                self._locked_by = owner
                self._locked_conn = conn
                return {"ok": True, "acquired": True}
        if op == "release":
            with self._cond:
                if self._locked_by == owner:
                    self._locked_by = None
                    self._locked_conn = None
                    self._cond.notify_all()
                    return {"ok": True, "released": True}
                return {"ok": True, "released": False}
        if op == "locked":
            with self._cond:
                return {"ok": True, "locked": self._locked_by is not None}
        return {"ok": False, "err": f"bad op {op}"}

    def on_disconnect(self, conn_id: str) -> None:
        # A holder whose connection died (process crash/OOM-kill) must
        # not leave the lock stuck forever — the whole point of the
        # flash-checkpoint path is surviving exactly that crash.
        with self._cond:
            if self._locked_conn == conn_id:
                logger.warning(
                    "lock %s holder disconnected; force-releasing",
                    self.name,
                )
                self._locked_by = None
                self._locked_conn = None
                self._cond.notify_all()


class SharedLock:
    """A named lock shared across processes on one host.

    The process constructed with ``server=True`` hosts the lock; all
    handles (including the server's own) go through the socket so lock
    semantics are identical regardless of which process holds a handle.
    """

    def __init__(self, name: str, server: bool = False):
        self.name = f"lock_{name}"
        self._server = _LockServer(self.name) if server else None
        self._client = _PrimitiveClient(self.name)
        self._owner = f"{os.getpid()}_{id(self)}"

    def acquire(self, blocking: bool = True) -> bool:
        resp = self._client.call(
            {"op": "acquire", "owner": self._owner, "blocking": blocking}
        )
        return bool(resp.get("acquired"))

    def release(self) -> bool:
        resp = self._client.call({"op": "release", "owner": self._owner})
        return bool(resp.get("released"))

    def locked(self) -> bool:
        return bool(self._client.call({"op": "locked"}).get("locked"))

    def __enter__(self):
        # acquire() can time out server-side (60s wait cap); never enter
        # the critical section without actually holding the lock.
        while not self.acquire():
            pass
        return self

    def __exit__(self, *exc):
        self.release()

    def close(self) -> None:
        self._client.close()
        if self._server is not None:
            self._server.close()


# ---------------------------------------------------------------------------
# SharedQueue
# ---------------------------------------------------------------------------


class _QueueServer(_PrimitiveServer):
    def __init__(self, name: str, maxsize: int = 0):
        self._queue: _queue.Queue = _queue.Queue(maxsize)
        super().__init__(name)

    def handle_request(self, req: dict) -> dict:
        op = req["op"]
        if op == "put":
            try:
                self._queue.put(
                    req["item"],
                    block=req.get("block", True),
                    timeout=req.get("timeout"),
                )
                return {"ok": True}
            except _queue.Full:
                return {"ok": False, "err": "full"}
        if op == "get":
            try:
                item = self._queue.get(
                    block=req.get("block", True), timeout=req.get("timeout")
                )
                return {"ok": True, "item": item}
            except _queue.Empty:
                return {"ok": False, "err": "empty"}
        if op == "qsize":
            return {"ok": True, "size": self._queue.qsize()}
        if op == "empty":
            return {"ok": True, "empty": self._queue.empty()}
        return {"ok": False, "err": f"bad op {op}"}


class SharedQueue:
    """A named FIFO queue shared across processes on one host.

    Items must be msgpack-serializable (numbers, strings, bytes, lists,
    maps) — checkpoint events are small dicts.
    """

    def __init__(self, name: str, server: bool = False, maxsize: int = 0):
        self.name = f"queue_{name}"
        self._server = _QueueServer(self.name, maxsize) if server else None
        self._client = _PrimitiveClient(self.name)

    # Blocking calls are chopped into short server-side waits so the
    # per-client socket lock is never held for an unbounded time (a
    # blocked get would otherwise deadlock a put from another thread of
    # the same process).
    _POLL_SECS = 0.2

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.time() + timeout
        while True:
            slice_timeout = 0 if not block else self._POLL_SECS
            resp = self._client.call(
                {"op": "put", "item": item, "block": block and slice_timeout > 0,
                 "timeout": slice_timeout}
            )
            if resp.get("ok"):
                return
            if not block:
                raise _queue.Full
            if deadline is not None and time.time() >= deadline:
                raise _queue.Full

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            slice_timeout = 0 if not block else self._POLL_SECS
            resp = self._client.call(
                {"op": "get", "block": block and slice_timeout > 0,
                 "timeout": slice_timeout}
            )
            if resp.get("ok"):
                return resp.get("item")
            if not block:
                raise _queue.Empty
            if deadline is not None and time.time() >= deadline:
                raise _queue.Empty

    def qsize(self) -> int:
        return int(self._client.call({"op": "qsize"}).get("size", 0))

    def empty(self) -> bool:
        return bool(self._client.call({"op": "empty"}).get("empty", True))

    def close(self) -> None:
        self._client.close()
        if self._server is not None:
            self._server.close()


# ---------------------------------------------------------------------------
# SharedDict
# ---------------------------------------------------------------------------


class _DictServer(_PrimitiveServer):
    def __init__(self, name: str):
        self._dict: Dict[str, Any] = {}
        self._lock = threading.Lock()
        super().__init__(name)

    def handle_request(self, req: dict) -> dict:
        op = req["op"]
        with self._lock:
            if op == "set":
                self._dict[req["key"]] = req["value"]
                return {"ok": True}
            if op == "get":
                if req["key"] in self._dict:
                    return {"ok": True, "found": True, "value": self._dict[req["key"]]}
                return {"ok": True, "found": False}
            if op == "update":
                self._dict.update(req["items"])
                return {"ok": True}
            if op == "all":
                return {"ok": True, "items": dict(self._dict)}
            if op == "pop":
                val = self._dict.pop(req["key"], None)
                return {"ok": True, "value": val}
        return {"ok": False, "err": f"bad op {op}"}


class SharedDict:
    """A named dict shared across processes on one host."""

    def __init__(self, name: str, server: bool = False):
        self.name = f"dict_{name}"
        self._server = _DictServer(self.name) if server else None
        self._client = _PrimitiveClient(self.name)

    def set(self, key: str, value: Any) -> None:
        self._client.call({"op": "set", "key": key, "value": value})

    def get(self, key: str, default: Any = None) -> Any:
        resp = self._client.call({"op": "get", "key": key})
        return resp["value"] if resp.get("found") else default

    def update(self, items: Dict[str, Any]) -> None:
        self._client.call({"op": "update", "items": items})

    def all(self) -> Dict[str, Any]:
        return self._client.call({"op": "all"}).get("items", {})

    def pop(self, key: str) -> Any:
        return self._client.call({"op": "pop", "key": key}).get("value")

    def close(self) -> None:
        self._client.close()
        if self._server is not None:
            self._server.close()


# ---------------------------------------------------------------------------
# SharedMemory wrapper
# ---------------------------------------------------------------------------


class SharedMemoryHandle:
    """POSIX shared memory that survives creator/attacher races.

    Parity with the reference's wrapper: creating an existing segment
    re-attaches (resizing if needed); unlink is idempotent. The resource
    tracker is disabled for attachers so an exiting trainer doesn't
    destroy the agent's segment.
    """

    def __init__(self, name: str, create: bool = False, size: int = 0):
        self.name = name.replace("/", "_")
        self._shm: Optional[shared_memory.SharedMemory] = None
        if create:
            try:
                self._shm = shared_memory.SharedMemory(
                    name=self.name, create=True, size=size
                )
            except FileExistsError:
                existing = shared_memory.SharedMemory(name=self.name)
                if existing.size >= size:
                    self._shm = existing
                    # This process is an attacher, not the creator: its
                    # resource tracker must not unlink the creator's
                    # segment at exit.
                    self._untrack()
                else:
                    existing.close()
                    existing.unlink()
                    self._shm = shared_memory.SharedMemory(
                        name=self.name, create=True, size=size
                    )
        else:
            self._shm = shared_memory.SharedMemory(name=self.name)
            self._untrack()

    def _untrack(self):
        # Attachers must not let the multiprocessing resource_tracker
        # unlink the segment when they exit.
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(self._shm._name, "shared_memory")  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001 - best effort, py-version dependent
            pass

    @property
    def buf(self) -> memoryview:
        assert self._shm is not None
        return self._shm.buf

    @property
    def size(self) -> int:
        assert self._shm is not None
        return self._shm.size

    def close(self) -> None:
        if self._shm is not None:
            self._shm.close()

    def unlink(self) -> None:
        if self._shm is not None:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass

    @staticmethod
    def exists(name: str) -> bool:
        try:
            shm = shared_memory.SharedMemory(name=name.replace("/", "_"))
        except FileNotFoundError:
            return False
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001
            pass
        shm.close()
        return True
