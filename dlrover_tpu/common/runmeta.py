"""Provenance stamps for bench/perf artifacts.

Every performance number this repo records must say *what measured
it*: host, backend, jax/jaxlib versions, git revision, and a hash of
the knobs that shaped the run — otherwise a "0.92x" from a CPU
fallback and a "0.92x" from the real chip are indistinguishable six
weeks later (the CKPT_r05 backend ambiguity). The helpers here are
the single source of those stamps, shared by ``bench.py``,
``tools/capture_perf.py``, ``tools/bench_stability.py``, and the
bench ledger (``tools/bench_ledger.py``).

Deliberately stdlib-only and jax-import-free: the bench *parent*
process never imports jax (a wedged tunnel must not hang it), so
toolchain versions come from package metadata, not the live module.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import socket
import subprocess
from typing import Dict, Iterable, Optional


def package_version(name: str) -> str:
    """Installed version of ``name`` without importing it."""
    try:
        from importlib.metadata import version

        return version(name)
    except Exception:  # noqa: BLE001 — absent package / broken dist
        return ""


def git_rev(repo: Optional[str] = None, short: bool = False) -> str:
    """HEAD revision of ``repo`` (default: this file's repo), "" when
    git is unavailable (stripped release trees)."""
    if repo is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    cmd = ["git", "rev-parse", "HEAD"]
    if short:
        cmd.insert(2, "--short")
    try:
        out = subprocess.run(
            cmd, cwd=repo, capture_output=True, text=True, timeout=10
        )
        return out.stdout.strip() if out.returncode == 0 else ""
    except (OSError, subprocess.TimeoutExpired):
        return ""


def run_metadata(
    backend: Optional[str] = None, extra: Optional[dict] = None
) -> Dict[str, str]:
    """The stamp every bench/perf artifact carries. ``backend`` comes
    from whoever actually touched the device (the bench child's
    ``jax.default_backend()``); callers that never import jax pass
    None and get the env's declared platform instead."""
    meta = {
        "host": socket.gethostname(),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "jax": package_version("jax"),
        "jaxlib": package_version("jaxlib"),
        "backend": (
            backend
            or os.getenv("JAX_PLATFORMS", "")
            or "undeclared"
        ),
    }
    if extra:
        meta.update({k: str(v) for k, v in extra.items()})
    return meta


def trial_fingerprint(parts: Dict) -> str:
    """Stable short hash identifying an autotune *trial context*: the
    things that, when any of them changes, invalidate a cached tuning
    result — model shape dims, mesh/device extent, kernel/op id,
    dtype, backend, and toolchain versions. Callers pass them as a
    flat JSON-serializable dict; key order never matters. This is the
    key of ``accelerate/tune_cache.py``'s trial store, kept here so
    jax-free tooling (the bench parent, ``tools/capture_perf.py``)
    can compute/compare keys without touching the accelerate package.
    """
    digest = hashlib.sha256(
        json.dumps(parts, sort_keys=True, default=str).encode()
    ).hexdigest()
    return digest[:16]


# BENCH_* variables that are bookkeeping, not measurement knobs: they
# must not perturb the config fingerprint (a capture_perf-driven run
# and an identically-knobbed manual run measured the same config).
# BENCH_IGNORE_TUNED stays IN the hash — it gates whether the pin
# file applies, which does change what was measured.
_NON_KNOB_ENV = frozenset(("BENCH_LEDGER_STAGE", "BENCH_NO_LEDGER"))


def config_fingerprint(
    env: Optional[dict] = None,
    prefixes: Iterable[str] = ("BENCH_",),
    extra_files: Iterable[str] = ("bench_tuned.json",),
    repo: Optional[str] = None,
) -> str:
    """Short stable hash of everything that shapes a bench run: the
    ``BENCH_*`` env knobs plus the autotune pin file's content. Two
    records with equal fingerprints measured the same configuration,
    so the ledger's compare gate diffs like against like."""
    if env is None:
        env = dict(os.environ)
    if repo is None:
        repo = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    knobs = {
        k: v
        for k, v in env.items()
        if any(k.startswith(p) for p in prefixes)
        and k not in _NON_KNOB_ENV
    }
    payload = {"env": knobs, "files": {}}
    for fname in extra_files:
        path = os.path.join(repo, fname)
        try:
            with open(path) as f:
                payload["files"][fname] = f.read()
        except OSError:
            pass
    digest = hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()
    ).hexdigest()
    return digest[:12]
