"""Checkpoint storage backends.

Capability parity with the reference's storage abstraction
(dlrover/python/common/storage.py — PosixDiskStorage with
write/read/safe_rmtree plus a pluggable CheckpointStorage base). The
TPU build keeps the same surface so the async saver is storage-agnostic;
a GCS backend can slot in for GKE pod-slices without touching the saver.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from abc import ABC, abstractmethod
from typing import List, Optional


class CheckpointStorage(ABC):
    """Minimal filesystem-like interface the async saver needs."""

    @abstractmethod
    def write_bytes(self, data: bytes, path: str) -> None: ...

    @abstractmethod
    def read_bytes(self, path: str) -> bytes: ...

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        """Read ``length`` bytes at ``offset``. Base implementation
        reads the whole object; backends with ranged reads (POSIX
        seek, GCS/S3 Range headers) override BOTH this and
        ``supports_range`` for streaming restore."""
        return self.read_bytes(path)[offset:offset + length]

    def supports_range(self) -> bool:
        """Whether read_range is a true ranged read. Streaming restore
        only engages when True — the base fallback would otherwise
        download the whole object once per requested range."""
        return False

    @abstractmethod
    def exists(self, path: str) -> bool: ...

    @abstractmethod
    def listdir(self, path: str) -> List[str]: ...

    @abstractmethod
    def makedirs(self, path: str) -> None: ...

    @abstractmethod
    def rmtree(self, path: str) -> None: ...

    @abstractmethod
    def rename(self, src: str, dst: str) -> None: ...


class PosixStorage(CheckpointStorage):
    """Local/NFS POSIX storage.

    Writes are atomic (temp file + rename) so a reader never sees a
    half-written shard — the commit protocol depends on done-files being
    all-or-nothing.
    """

    def write_bytes(self, data: bytes, path: str) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d or ".", suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(length)

    def supports_range(self) -> bool:
        return True

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def rmtree(self, path: str) -> None:
        shutil.rmtree(path, ignore_errors=True)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)


def get_storage(kind: Optional[str] = None) -> CheckpointStorage:
    """Factory. ``kind`` defaults to env DLROVER_TPU_CKPT_STORAGE."""
    kind = kind or os.getenv("DLROVER_TPU_CKPT_STORAGE", "posix")
    if kind == "posix":
        return PosixStorage()
    raise ValueError(f"unknown checkpoint storage backend: {kind}")
