"""Structured logging for all dlrover-tpu processes.

One shared logger (parity: dlrover/python/common/log.py) with a
rank/role-aware format so interleaved multi-process logs stay readable.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(name)s:%(lineno)d] %(message)s"
)


def _build_logger(name: str = "dlrover_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if logger.handlers:
        return logger
    level = os.getenv("DLROVER_TPU_LOG_LEVEL", "INFO").upper()
    logger.setLevel(level)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    logger.propagate = False
    return logger


default_logger = _build_logger()


def get_logger(name: str) -> logging.Logger:
    """Child logger that inherits the default handler/format."""
    logger = default_logger.getChild(name)
    return logger
