"""Structured logging for all dlrover-tpu processes.

One shared logger (parity: dlrover/python/common/log.py) with a
rank/role-aware format so interleaved multi-process logs stay
readable: role comes from ``DLROVER_TPU_ROLE`` (stamped by the elastic
launcher), rank from ``JAX_PROCESS_INDEX`` or
``DLROVER_TPU_NODE_RANK``. Setting ``DLROVER_TPU_LOG_JSON=1`` switches
to machine-readable JSON lines (one object per record) for log
pipelines.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] [%(role_rank)s] "
    "[%(name)s:%(lineno)d] %(message)s"
)


def role_and_rank() -> tuple:
    """(role, rank) of this process from the environment — the single
    definition of that contract, shared with the obs tracer's event
    tags. Role comes from ``DLROVER_TPU_ROLE`` (stamped by the elastic
    launcher), rank from ``JAX_PROCESS_INDEX`` falling back to
    ``DLROVER_TPU_NODE_RANK``; rank is -1 when absent/unparsable. Read
    per-call: the launcher/agent may set the vars after import."""
    role = os.getenv("DLROVER_TPU_ROLE", "") or ""
    rank_s = os.getenv(
        "JAX_PROCESS_INDEX", os.getenv("DLROVER_TPU_NODE_RANK", "")
    )
    try:
        rank = int(rank_s)
    except ValueError:
        rank = -1
    return role, rank


def _role_rank() -> str:
    """``role/rank`` log tag, e.g. ``worker/0``."""
    role, rank = role_and_rank()
    role = role or "-"
    return f"{role}/{rank}" if rank >= 0 else role


class _TextFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        record.role_rank = _role_rank()
        return super().format(record)


class _JsonFormatter(logging.Formatter):
    """One JSON object per record (DLROVER_TPU_LOG_JSON=1)."""

    def format(self, record: logging.LogRecord) -> str:
        role, rank = role_and_rank()
        role = role or "-"
        payload = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "line": record.lineno,
            "role": role,
            "rank": rank,
            "pid": record.process,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


def _make_formatter() -> logging.Formatter:
    if os.getenv("DLROVER_TPU_LOG_JSON", "") == "1":
        return _JsonFormatter()
    return _TextFormatter(_FORMAT)


def _build_logger(name: str = "dlrover_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if logger.handlers:
        return logger
    level = os.getenv("DLROVER_TPU_LOG_LEVEL", "INFO").upper()
    logger.setLevel(level)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_make_formatter())
    logger.addHandler(handler)
    logger.propagate = False
    return logger


default_logger = _build_logger()


def reconfigure() -> None:
    """Re-read the env knobs (JSON mode, level) onto the existing
    handlers — for processes that set them after import."""
    default_logger.setLevel(
        os.getenv("DLROVER_TPU_LOG_LEVEL", "INFO").upper()
    )
    for handler in default_logger.handlers:
        handler.setFormatter(_make_formatter())


def get_logger(name: str) -> logging.Logger:
    """Child logger that inherits the default handler/format."""
    logger = default_logger.getChild(name)
    return logger
