"""Deterministic, seeded control-plane fault injector.

The chaos harness exists so the control plane's survivability claims
(master warm restart, reconnecting agents) are *continuously* proven
under injected faults instead of asserted once. It is wired into the
RPC transport (``common/comm.py``): the client side can drop requests,
add latency, or substitute transport errors; the server side can kill
the master process when the Nth request of a given type arrives —
which is how the failover drills schedule "master dies mid-sharded-run"
without racing on wall time.

Design constraints:

* **Deterministic from a seed.** All randomness flows from one
  ``random.Random(seed)`` drawn in a fixed per-call pattern under a
  lock, so the same seed and call sequence produce the same fault
  schedule (asserted by tests/test_master_failover.py). The drawn
  decisions are kept in a bounded ``decisions`` log for drills to
  diff.
* **Env-gated and zero-cost when off.** Nothing is injected unless
  ``DLROVER_TPU_CHAOS=1``; the comm-layer hook is a module-level
  None-check.
* **Faults look like real faults.** Drops and partitions raise
  :class:`ChaosDropError` (a ``ConnectionError``), which the agent's
  connection supervisor classifies as *transient* — exactly like a
  dead master — so chaos exercises the same reconnect machinery a
  real outage does.
"""

from __future__ import annotations

import collections
import os
import random
import sys
import threading
import time
from typing import Deque, Optional, Sequence, Tuple

from dlrover_tpu.common.log import get_logger

logger = get_logger("chaos")

CHAOS_ENV = "DLROVER_TPU_CHAOS"
SEED_ENV = "DLROVER_TPU_CHAOS_SEED"
DROP_RATE_ENV = "DLROVER_TPU_CHAOS_DROP_RATE"
ERROR_RATE_ENV = "DLROVER_TPU_CHAOS_ERROR_RATE"
LATENCY_MS_ENV = "DLROVER_TPU_CHAOS_LATENCY_MS"
PARTITION_NODES_ENV = "DLROVER_TPU_CHAOS_PARTITION_NODES"
# Server-side: "MessageTypeName:N" — _exit the process when the Nth
# request of that type is dispatched (N counts from 1).
KILL_AT_ENV = "DLROVER_TPU_CHAOS_KILL_AT"
# Which RPC plane client-side faults apply to: "all" (default),
# "master" (agent<->master control plane only) or "ps" (trainer<->PS
# data plane only — Ps* request types). Out-of-scope calls still draw
# from the RNG so the fault schedule of in-scope calls is unchanged
# by scoping (same seed => same decisions at the same call indices).
SCOPE_ENV = "DLROVER_TPU_CHAOS_SCOPE"

# Exit code for a chaos-scheduled master kill: distinguishable from
# OOM (137) and ordinary failures in drill logs.
KILL_EXIT_CODE = 43


class ChaosDropError(ConnectionError):
    """A chaos-injected request drop / partition.

    Subclasses ``ConnectionError`` so the reconnect supervisor's
    transient-error classification treats it like a real dead socket.
    """


class ChaosPartitionError(ChaosDropError):
    """This node is chaos-partitioned from the master."""


class ChaosInjector:
    """One injector per process; decisions are drawn serially.

    ``node_id`` identifies the local node for partition checks (None
    = read ``DLROVER_TPU_NODE_ID`` lazily, so the injector can be
    built before the agent env is final).
    """

    MAX_DECISIONS = 10000

    def __init__(
        self,
        seed: int = 0,
        drop_rate: float = 0.0,
        error_rate: float = 0.0,
        latency_ms: float = 0.0,
        partition_nodes: Sequence[int] = (),
        kill_at: Optional[Tuple[str, int]] = None,
        node_id: Optional[int] = None,
        scope: str = "all",
    ):
        self.seed = seed
        self.drop_rate = drop_rate
        self.error_rate = error_rate
        self.latency_ms = latency_ms
        self.partition_nodes = frozenset(int(n) for n in partition_nodes)
        self.kill_at = kill_at
        if scope not in ("all", "master", "ps"):
            raise ValueError(
                f"chaos scope must be all|master|ps, got {scope!r}"
            )
        self.scope = scope
        self._node_id = node_id
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._calls = 0
        self._server_counts: dict = {}
        #: (call_index, method, decision) log, bounded; drills diff it
        #: across runs to prove seed-reproducibility.
        self.decisions: Deque[Tuple[int, str, str]] = collections.deque(
            maxlen=self.MAX_DECISIONS
        )

    @classmethod
    def from_env(cls, environ=os.environ) -> "ChaosInjector":
        kill_at = None
        raw = environ.get(KILL_AT_ENV, "")
        if raw:
            name, _, count = raw.partition(":")
            kill_at = (name.strip(), int(count) if count else 1)
        nodes = [
            int(p)
            for p in environ.get(PARTITION_NODES_ENV, "").split(",")
            if p.strip()
        ]
        return cls(
            seed=int(environ.get(SEED_ENV, "0") or 0),
            drop_rate=float(environ.get(DROP_RATE_ENV, "0") or 0),
            error_rate=float(environ.get(ERROR_RATE_ENV, "0") or 0),
            latency_ms=float(environ.get(LATENCY_MS_ENV, "0") or 0),
            partition_nodes=nodes,
            kill_at=kill_at,
            scope=environ.get(SCOPE_ENV, "all") or "all",
        )

    def _local_node_id(self) -> Optional[int]:
        if self._node_id is not None:
            return self._node_id
        raw = os.getenv("DLROVER_TPU_NODE_ID", "")
        return int(raw) if raw else None

    def _draw(self) -> Tuple[int, float, float, float]:
        """One decision draw: always three uniforms in fixed order so
        the schedule depends only on (seed, call index), never on
        which fault kinds are enabled."""
        with self._lock:
            index = self._calls
            self._calls += 1
            u_drop = self._rng.random()
            u_err = self._rng.random()
            u_jitter = self._rng.random()
        return index, u_drop, u_err, u_jitter

    def decide(self, method: str) -> Tuple[str, float]:
        """(decision, latency_s) for one client call.

        decision: "pass" | "drop" | "error" | "partition". Latency
        applies to passing calls (0..latency_ms, jittered)."""
        index, u_drop, u_err, u_jitter = self._draw()
        node_id = self._local_node_id()
        if node_id is not None and node_id in self.partition_nodes:
            decision = "partition"
        elif u_drop < self.drop_rate:
            decision = "drop"
        elif u_err < self.error_rate:
            decision = "error"
        else:
            decision = "pass"
        latency_s = (self.latency_ms / 1000.0) * u_jitter
        self.decisions.append((index, method, decision))
        return decision, latency_s

    # -- client side ------------------------------------------------------

    def _in_scope(self, request) -> bool:
        """Does the configured scope cover this request's plane? The
        PS data plane is identified by its message types (Ps*) — the
        same RpcClient carries both planes, so the stub name alone
        cannot distinguish them."""
        if self.scope == "all":
            return True
        is_ps = type(request).__name__.startswith("Ps")
        return is_ps if self.scope == "ps" else not is_ps

    def before_client_call(self, method: str, request) -> None:
        """Raise/delay per the schedule. Called by RpcClient._call."""
        decision, latency_s = self.decide(method)
        if not self._in_scope(request):
            # The draw already happened (schedule stability); the
            # fault just doesn't apply to this plane.
            return
        if decision == "partition":
            raise ChaosPartitionError(
                f"chaos: node {self._local_node_id()} is partitioned "
                "from the master"
            )
        if decision == "drop":
            raise ChaosDropError(
                f"chaos: dropped {type(request).__name__} ({method})"
            )
        if decision == "error":
            raise ChaosDropError(
                f"chaos: transport error substituted for "
                f"{type(request).__name__} ({method})"
            )
        if latency_s > 0:
            time.sleep(latency_s)

    # -- server side ------------------------------------------------------

    def on_server_request(self, request) -> None:
        """Kill-master-at-event: exit the process when the Nth request
        of the configured type arrives. Called by the RPC server's
        generic handler before dispatch."""
        if self.kill_at is None:
            return
        name = type(request).__name__
        want_name, want_count = self.kill_at
        if name != want_name:
            return
        with self._lock:
            self._server_counts[name] = self._server_counts.get(name, 0) + 1
            count = self._server_counts[name]
        if count >= want_count:
            logger.error(
                "chaos: killing this process at %s #%d (seed=%d)",
                name, count, self.seed,
            )
            sys.stderr.flush()
            os._exit(KILL_EXIT_CODE)


# -- module-level gate --------------------------------------------------------

_injector: Optional[ChaosInjector] = None
_init_done = False
_init_lock = threading.Lock()


def get_injector() -> Optional[ChaosInjector]:
    """The process's env-gated injector, or None when chaos is off."""
    global _injector, _init_done
    if _init_done:
        return _injector
    with _init_lock:
        if not _init_done:
            if os.getenv(CHAOS_ENV, "") == "1":
                _injector = ChaosInjector.from_env()
                logger.warning(
                    "chaos injection ENABLED (seed=%d drop=%.3f "
                    "error=%.3f latency=%.0fms partition=%s kill_at=%s "
                    "scope=%s)",
                    _injector.seed,
                    _injector.drop_rate,
                    _injector.error_rate,
                    _injector.latency_ms,
                    sorted(_injector.partition_nodes),
                    _injector.kill_at,
                    _injector.scope,
                )
            _init_done = True
    return _injector


def install_injector(injector: Optional[ChaosInjector]) -> None:
    """Explicitly install (tests) or clear (None) the injector."""
    global _injector, _init_done
    with _init_lock:
        _injector = injector
        _init_done = True


def reset() -> None:
    """Forget the cached env decision (tests that flip the env)."""
    global _injector, _init_done
    with _init_lock:
        _injector = None
        _init_done = False
