"""Control-plane RPC transport.

Same 2-RPC shape as the reference master service
(proto/elastic_training.proto:28-31: ``report`` fire-and-forget-ish and
``get`` request/response), but built with gRPC *generic handlers* and the
typed msgpack schema from ``messages.py`` — no protoc codegen, no pickle.

The server dispatches on the request dataclass type; handlers are
registered per message class.
"""

from __future__ import annotations

import socket
import threading
from concurrent import futures
from typing import Any, Callable, Dict, Optional, Type

import grpc

from dlrover_tpu.common import messages
from dlrover_tpu.common.constants import GrpcEnv
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs import tracer as _trace

logger = get_logger("comm")

SERVICE_NAME = "dlrover_tpu.Master"
_GET = f"/{SERVICE_NAME}/get"
_REPORT = f"/{SERVICE_NAME}/report"

_GRPC_OPTIONS = [
    ("grpc.max_send_message_length", GrpcEnv.MAX_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GrpcEnv.MAX_MESSAGE_LENGTH),
    # Reconnect fast after a master bounce: gRPC's default connect
    # backoff grows toward 120s, which would leave a client failing
    # instantly ("failed to connect to all addresses") long after the
    # replacement master is up — the agent's outage budget would burn
    # on channel backoff, not on the actual outage.
    ("grpc.initial_reconnect_backoff_ms", 100),
    ("grpc.min_reconnect_backoff_ms", 100),
    ("grpc.max_reconnect_backoff_ms", 2000),
]


def _chaos_injector():
    """Env-gated chaos injector (common/chaos.py); None when off."""
    from dlrover_tpu.common import chaos

    return chaos.get_injector()


def _chaos_server_hook(request) -> None:
    inj = _chaos_injector()
    if inj is not None:
        inj.on_server_request(request)


class _TracedPayload:
    """Client-side carrier pairing a request with its envelope fields
    (``_tc`` trace context, ``_job`` routing id) for the gRPC
    serializer — per-call state the stub's fixed
    ``request_serializer`` could not otherwise see."""

    __slots__ = ("msg", "trace", "job_id")

    def __init__(
        self,
        msg: Any,
        trace: Optional[Dict[str, str]],
        job_id: str = "",
    ):
        self.msg = msg
        self.trace = trace
        self.job_id = job_id


def _serialize_request(obj: Any) -> bytes:
    if isinstance(obj, _TracedPayload):
        return messages.serialize(
            obj.msg, trace=obj.trace, job_id=obj.job_id
        )
    return messages.serialize(obj)


def find_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class RpcDispatcher:
    """Routes decoded request messages to per-type handler callables.

    ``job_id`` (the envelope's ``_job`` field) is accepted — and
    ignored — by the base dispatcher: a single-job master serves
    every caller identically, so a job-tagged client talking to one
    keeps working. :class:`JobRoutingDispatcher` overrides the
    handle methods to route on it."""

    def __init__(self):
        self._get_handlers: Dict[type, Callable[[Any], Any]] = {}
        self._report_handlers: Dict[type, Callable[[Any], Any]] = {}

    def register_get(self, msg_cls: type, fn: Callable[[Any], Any]) -> None:
        self._get_handlers[msg_cls] = fn

    def register_report(self, msg_cls: type, fn: Callable[[Any], Any]) -> None:
        self._report_handlers[msg_cls] = fn

    def has_get(self, msg_cls: type) -> bool:
        return msg_cls in self._get_handlers

    def has_report(self, msg_cls: type) -> bool:
        return msg_cls in self._report_handlers

    def handle_get(self, request: Any, job_id: str = "") -> Any:
        fn = self._get_handlers.get(type(request))
        if fn is None:
            raise KeyError(f"no get handler for {type(request).__name__}")
        return fn(request)

    def handle_report(self, request: Any, job_id: str = "") -> Any:
        fn = self._report_handlers.get(type(request))
        if fn is None:
            raise KeyError(f"no report handler for {type(request).__name__}")
        return fn(request)


class JobRoutingDispatcher(RpcDispatcher):
    """Multi-job dispatcher: the pool master's one RPC server hosting
    many per-job servicers.

    Requests whose envelope carries a ``_job`` id route to that job's
    registered :class:`RpcDispatcher` (its own node table, rendezvous,
    shard ledger, kv store); pool-level messages — and any message
    type a job's servicer does not handle, e.g. TraceQueryRequest
    served by the shared trace store — fall through to the handlers
    registered directly on this dispatcher. An unknown job id raises,
    so a worker of a retired job fails loudly instead of silently
    mutating another job's state."""

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._jobs: Dict[str, RpcDispatcher] = {}

    def register_job(
        self, job_id: str, dispatcher: RpcDispatcher
    ) -> None:
        if not job_id:
            raise ValueError("job_id must be non-empty")
        with self._lock:
            self._jobs[job_id] = dispatcher

    def remove_job(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)

    def job_ids(self) -> list:
        with self._lock:
            return sorted(self._jobs)

    def _job_dispatcher(self, job_id: str) -> RpcDispatcher:
        with self._lock:
            d = self._jobs.get(job_id)
        if d is None:
            raise KeyError(
                f"unknown job {job_id!r} (known: {self.job_ids()})"
            )
        return d

    def handle_get(self, request: Any, job_id: str = "") -> Any:
        if job_id:
            d = self._job_dispatcher(job_id)
            if d.has_get(type(request)):
                return d.handle_get(request)
        return super().handle_get(request)

    def handle_report(self, request: Any, job_id: str = "") -> Any:
        if job_id:
            d = self._job_dispatcher(job_id)
            if d.has_report(type(request)):
                return d.handle_report(request)
        return super().handle_report(request)


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, dispatcher: RpcDispatcher):
        self._dispatcher = dispatcher

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == _GET:
            return grpc.unary_unary_rpc_method_handler(
                self._do_get,
                request_deserializer=messages.deserialize_envelope,
                response_serializer=messages.serialize,
            )
        if method == _REPORT:
            return grpc.unary_unary_rpc_method_handler(
                self._do_report,
                request_deserializer=messages.deserialize_envelope,
                response_serializer=messages.serialize,
            )
        return None

    def _dispatch(self, handle, payload, what: str):
        request, trace, job_id = payload
        _chaos_server_hook(request)
        # Re-activate the caller's trace context for the handler: the
        # spans/events the master emits while serving this RPC land in
        # the caller's causal timeline. Malformed carriers extract to
        # None and cost nothing.
        ctx = _trace.extract(trace) if trace else None
        try:
            if ctx is not None:
                with _trace.activate(ctx):
                    result = handle(request, job_id)
            else:
                result = handle(request, job_id)
            return messages.BaseResponse(success=True, data=result)
        except Exception as e:  # noqa: BLE001 - must not kill the server
            logger.exception(
                "%s(%s) failed", what, type(request).__name__
            )
            return messages.BaseResponse(success=False, message=str(e))

    def _do_get(self, payload, context):
        return self._dispatch(
            self._dispatcher.handle_get, payload, "get"
        )

    def _do_report(self, payload, context):
        return self._dispatch(
            self._dispatcher.handle_report, payload, "report"
        )


class RpcServer:
    """gRPC server hosting the master service."""

    def __init__(
        self,
        dispatcher: RpcDispatcher,
        port: int = 0,
        max_workers: int = 16,
    ):
        self.dispatcher = dispatcher
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_GRPC_OPTIONS,
        )
        self._server.add_generic_rpc_handlers([_GenericHandler(dispatcher)])
        self.port = self._server.add_insecure_port(f"0.0.0.0:{port}")

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> None:
        self._server.start()
        logger.info("master RPC server listening on port %d", self.port)

    def stop(self, grace: Optional[float] = None) -> None:
        self._server.stop(grace)


class RpcError(RuntimeError):
    pass


class RpcClient:
    """Client to the master service; thread-safe, lazily connected.

    ``wait_for_ready`` is the per-client default for queue-until-
    connected RPC semantics (overridable per call): True suits
    clients of the (warm-restartable) master, whose supervisor wants
    calls to wait out a channel in TRANSIENT_FAILURE; the default
    False (fail fast) suits clients of peers that are REPLACED rather
    than restarted in place (PS hosts, ingest workers) — their
    callers own a refetch/retry loop and need dead-peer calls to
    fail instantly, not block a step for the full RPC timeout."""

    def __init__(
        self,
        addr: str,
        timeout: float = 30.0,
        wait_for_ready: bool = False,
        job_id: str = "",
    ):
        self.addr = addr
        self.timeout = timeout
        self.wait_for_ready = wait_for_ready
        # Stamped on every request's envelope (the ``_job`` field) so
        # a pool master routes this client's calls to its job's
        # servicer. "" = single-job client (envelope field omitted).
        self.job_id = job_id
        self._lock = threading.Lock()
        self._channel: Optional[grpc.Channel] = None
        self._get: Optional[grpc.UnaryUnaryMultiCallable] = None
        self._report: Optional[grpc.UnaryUnaryMultiCallable] = None

    def _connect(self):
        with self._lock:
            if self._channel is not None:
                return
            self._channel = grpc.insecure_channel(
                self.addr, options=_GRPC_OPTIONS
            )
            self._get = self._channel.unary_unary(
                _GET,
                request_serializer=_serialize_request,
                response_deserializer=messages.deserialize,
            )
            self._report = self._channel.unary_unary(
                _REPORT,
                request_serializer=_serialize_request,
                response_deserializer=messages.deserialize,
            )

    def _call(
        self,
        stub_name: str,
        request: Any,
        timeout: Optional[float],
        wait_for_ready: Optional[bool] = None,
    ):
        if wait_for_ready is None:
            wait_for_ready = self.wait_for_ready
        inj = _chaos_injector()
        if inj is not None:
            # May sleep (added latency) or raise ChaosDropError /
            # ChaosPartitionError, which the reconnect supervisor
            # classifies as transient — same path as a dead master.
            inj.before_client_call(stub_name, request)
        self._connect()
        stub = self._get if stub_name == "get" else self._report
        # Propagate the active trace context (if any) as the request
        # envelope's _tc field, and the client's job id as _job.
        # inject() is a dict lookup + None when no trace is active —
        # the single-job, no-trace common case stays allocation-free.
        carrier = _trace.inject()
        payload = (
            _TracedPayload(request, carrier, self.job_id)
            if carrier is not None or self.job_id
            else request
        )
        # wait_for_ready=True queues the RPC until the channel
        # (re)connects instead of failing fast from TRANSIENT_FAILURE
        # — without it a channel that ever saw the master down keeps
        # failing instantly long after the master is back, burning
        # the reconnect budget on channel state instead of the actual
        # outage. Best-effort telemetry passes False: it must DROP
        # fast during an outage, not block a reporting loop.
        response = stub(
            payload,
            timeout=timeout or self.timeout,
            wait_for_ready=wait_for_ready,
        )
        if not isinstance(response, messages.BaseResponse):
            raise RpcError(f"bad response type {type(response).__name__}")
        if not response.success:
            raise RpcError(response.message)
        return response.data

    def get(
        self,
        request: Any,
        timeout: Optional[float] = None,
        wait_for_ready: Optional[bool] = None,
    ) -> Any:
        return self._call("get", request, timeout, wait_for_ready)

    def report(
        self,
        request: Any,
        timeout: Optional[float] = None,
        wait_for_ready: Optional[bool] = None,
    ) -> Any:
        return self._call("report", request, timeout, wait_for_ready)

    def close(self) -> None:
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
