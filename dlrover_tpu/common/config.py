"""Global job context (singleton) and env-driven configuration.

Parity: dlrover/python/common/global_context.py:190 ``Context``. Values
come from env vars first, then master-pushed overrides.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

from dlrover_tpu.common.constants import DefaultValues, NodeEnv, PlatformType


def env_int(name: str, default: int) -> int:
    v = os.getenv(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def env_str(name: str, default: str = "") -> str:
    return os.getenv(name, default)


def ensure_framework_on_pythonpath(env: Dict[str, str]) -> Dict[str, str]:
    """Make subprocesses able to ``import dlrover_tpu`` regardless of
    their cwd or script location.

    Python puts the *script's* directory — not the cwd — on
    ``sys.path``, so a training script living elsewhere would not find
    an uninstalled framework checkout. Prepend the package root to
    PYTHONPATH (launcher parity: torchrun relies on pip-installation
    instead; we support running straight from a checkout).
    """
    import dlrover_tpu

    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(dlrover_tpu.__file__))
    )
    existing = env.get("PYTHONPATH", "")
    parts = [p for p in existing.split(os.pathsep) if p]
    if pkg_root not in parts:
        env["PYTHONPATH"] = os.pathsep.join([pkg_root] + parts)
    return env


def env_bool(name: str, default: bool = False) -> bool:
    v = os.getenv(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


class Context:
    """Process-wide configuration singleton."""

    _instance: Optional["Context"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.job_name = env_str(NodeEnv.JOB_NAME, "local-job")
        self.master_addr = env_str(NodeEnv.MASTER_ADDR, "")
        self.node_id = env_int(NodeEnv.NODE_ID, 0)
        self.node_rank = env_int(NodeEnv.NODE_RANK, 0)
        self.node_num = env_int(NodeEnv.NODE_NUM, 1)
        self.platform = env_str(NodeEnv.PLATFORM, PlatformType.LOCAL)

        self.rdzv_timeout_secs = DefaultValues.RDZV_TIMEOUT_SECS
        self.pending_timeout_secs = DefaultValues.PENDING_TIMEOUT_SECS
        self.hang_timeout_secs = DefaultValues.HANG_TIMEOUT_SECS
        self.shard_timeout_secs = DefaultValues.SHARD_TIMEOUT_SECS
        self.relaunch_max = DefaultValues.RELAUNCH_MAX
        self.report_interval_secs = DefaultValues.REPORT_INTERVAL_SECS

        self.seconds_to_wait_pending_pod = 900
        self.master_port = DefaultValues.MASTER_PORT

        # Master-pushed overrides (e.g. from the brain/auto-tuner).
        self._overrides: Dict[str, Any] = {}

    @classmethod
    def singleton(cls) -> "Context":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Testing hook: drop the singleton so env changes take effect."""
        with cls._lock:
            cls._instance = None

    def apply_overrides(self, overrides: Dict[str, Any]) -> None:
        self._overrides.update(overrides)
        for k, v in overrides.items():
            if hasattr(self, k) and not k.startswith("_"):
                setattr(self, k, v)


def get_context() -> Context:
    return Context.singleton()
