// KvStore: host-side dynamically-growing embedding store.
//
// TPU-native counterpart of tfplus's KvVariable kernel suite
// (tfplus/tfplus/kv_variable/kernels/kv_variable.h:1021LoC template +
// hashmap.h concurrent map + kv_variable_ops.cc gather/insert kernels
// + training_ops.cc fused sparse optimizers). Design differences:
//
// * The reference embeds into TensorFlow's resource/variant machinery;
//   here the store is a plain C++ library with a C ABI consumed from
//   Python via ctypes and bridged into JAX with pure_callback — the
//   TPU has no unified memory, so sparse state intentionally lives on
//   the host and only the gathered minibatch rows travel to the chip.
// * Sharded locking (per-shard mutex over std::unordered_map) instead
//   of a custom concurrent map: shards bound contention between the
//   trainer's gather/apply thread and background export/evict.
// * Per-key frequency and version (last-update step) support the same
//   under/over-flow eviction policies as the reference
//   (kernels/hybrid_embedding/storage_table.h) and delta export for
//   incremental checkpoints (kv_variable.h full/incremental export).
//
// Fused sparse optimizers: adam, adagrad, ftrl, momentum — the subset
// of the reference's ~30 (training_ops.cc) that covers its grouped
// CTR workloads; each touches param + slot stores under one shard
// pass.

#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Slot {
  uint32_t offset;     // row index into the arena
  uint32_t frequency;  // gather count
  int64_t version;     // last update step
};

// Disk tier of the hybrid store (ref tfplus hybrid_embedding/
// storage_table.h MemStorageTable + remote tier, table_manager.h
// under/over-flow handling): cold rows spill to an append-only file
// of fixed records [dim floats | freq u32 | version i64]; hot-path
// access promotes them back. pread/pwrite keep IO thread-safe under
// per-shard locks.
class DiskTier {
 public:
  DiskTier(const std::string& path, int dim)
      : dim_(dim), record_bytes_(dim * sizeof(float) + sizeof(uint32_t) +
                                 sizeof(int64_t)) {
    fd_ = ::open(path.c_str(), O_RDWR | O_CREAT, 0644);
    ok_ = fd_ >= 0;
  }

  ~DiskTier() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return ok_; }

  static constexpr uint64_t kBadOffset = ~0ULL;

  uint64_t write_row(const float* row, uint32_t freq, int64_t version) {
    uint64_t off;
    {
      std::lock_guard<std::mutex> g(alloc_mu_);
      if (!free_.empty()) {
        off = free_.back();
        free_.pop_back();
      } else {
        off = next_;
        next_ += record_bytes_;
      }
    }
    std::vector<char> rec(record_bytes_);
    std::memcpy(rec.data(), row, dim_ * sizeof(float));
    std::memcpy(rec.data() + dim_ * sizeof(float), &freq, sizeof(freq));
    std::memcpy(rec.data() + dim_ * sizeof(float) + sizeof(freq), &version,
                sizeof(version));
    ssize_t n = ::pwrite(fd_, rec.data(), record_bytes_,
                         static_cast<off_t>(off));
    if (n != static_cast<ssize_t>(record_bytes_)) {
      // short write (ENOSPC, EINTR...): the record is unusable —
      // give the offset back and tell the caller to keep the row in
      // RAM rather than silently losing trained state
      release(off);
      return kBadOffset;
    }
    return off;
  }

  bool read_row(uint64_t off, float* row, uint32_t* freq,
                int64_t* version) const {
    std::vector<char> rec(record_bytes_);
    ssize_t n = ::pread(fd_, rec.data(), record_bytes_,
                        static_cast<off_t>(off));
    if (n != static_cast<ssize_t>(record_bytes_)) return false;
    std::memcpy(row, rec.data(), dim_ * sizeof(float));
    std::memcpy(freq, rec.data() + dim_ * sizeof(float), sizeof(*freq));
    std::memcpy(version, rec.data() + dim_ * sizeof(float) + sizeof(*freq),
                sizeof(*version));
    return true;
  }

  void release(uint64_t off) {
    std::lock_guard<std::mutex> g(alloc_mu_);
    free_.push_back(off);
  }

 private:
  int dim_;
  size_t record_bytes_;
  int fd_ = -1;
  bool ok_ = false;
  std::mutex alloc_mu_;
  uint64_t next_ = 0;
  std::vector<uint64_t> free_;
};

class KvStore {
 public:
  // init_mode: 0 = deterministic per-key uniform in [-scale, scale)
  // (embedding params), 1 = zeros (adam/momentum slots), 2 = constant
  // init_scale (ftrl accumulators need a positive floor).
  KvStore(int dim, uint64_t seed, int num_shards, float init_scale,
          int init_mode)
      : dim_(dim),
        seed_(seed),
        init_scale_(init_scale),
        init_mode_(init_mode),
        shards_(num_shards) {
    for (auto& s : shards_) {
      s.arena.reserve(1024 * dim_);
    }
  }

  ~KvStore() { delete disk_; }

  int dim() const { return dim_; }

  // Enable the hybrid RAM/disk tier: at most ``max_ram_rows`` rows
  // stay resident; the coldest (lowest frequency, then oldest
  // version) spill to ``path``. Returns false if the file can't open.
  // One-shot: re-targeting an active tier would orphan every spilled
  // offset (they index the OLD file). Budget granularity is
  // per-shard with a floor of 1, so the effective resident cap is
  // max(max_ram_rows, num_shards).
  bool set_disk_tier(const char* path, int64_t max_ram_rows) {
    if (disk_ != nullptr) return false;
    auto tier = new DiskTier(path, dim_);
    if (!tier->ok()) {
      delete tier;
      return false;
    }
    per_shard_budget_ =
        std::max<int64_t>(max_ram_rows / static_cast<int64_t>(
                                             shards_.size()),
                          1);
    disk_ = tier;
    return true;
  }

  int64_t ram_size() const {
    int64_t n = 0;
    for (auto& s : shards_) {
      std::lock_guard<std::mutex> g(s.mu);
      n += static_cast<int64_t>(s.map.size());
    }
    return n;
  }

  int64_t disk_size() const {
    if (!disk_) return 0;
    int64_t n = 0;
    for (auto& s : shards_) {
      std::lock_guard<std::mutex> g(s.mu);
      n += static_cast<int64_t>(s.disk_index.size());
    }
    return n;
  }

  int64_t size() const { return ram_size() + disk_size(); }

  // Deterministic per-key init: splitmix64 stream keyed by (seed, key)
  // so re-inserting an evicted key reproduces its initial row.
  void init_row(int64_t key, float* out) const {
    if (init_mode_ == 1) {
      std::memset(out, 0, sizeof(float) * dim_);
      return;
    }
    if (init_mode_ == 2) {
      for (int i = 0; i < dim_; ++i) out[i] = init_scale_;
      return;
    }
    uint64_t x = seed_ ^ (static_cast<uint64_t>(key) * 0x9E3779B97F4A7C15ULL);
    for (int i = 0; i < dim_; ++i) {
      x += 0x9E3779B97F4A7C15ULL;
      uint64_t z = x;
      z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
      z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
      z = z ^ (z >> 31);
      // uniform [-1, 1) scaled
      out[i] =
          init_scale_ *
          (static_cast<float>(z >> 11) * (1.0f / 4503599627370496.0f) - 1.0f);
    }
  }

  void gather(const int64_t* keys, int64_t n, float* out, bool insert_missing,
              bool count_frequency) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t key = keys[i];
      Shard& s = shard_for(key);
      std::lock_guard<std::mutex> g(s.mu);
      auto it = s.map.find(key);
      if (it == s.map.end()) {
        if (!insert_missing) {
          // inference path never mutates tiers: serve cold rows
          // straight from disk without promotion
          if (disk_) {
            auto dit = s.disk_index.find(key);
            if (dit != s.disk_index.end()) {
              uint32_t freq;
              int64_t version;
              if (disk_->read_row(dit->second, out + i * dim_, &freq,
                                  &version)) {
                continue;
              }
            }
          }
          std::memset(out + i * dim_, 0, sizeof(float) * dim_);
          continue;
        }
        it = insert_locked(s, key);  // promotes from disk if spilled
      }
      if (count_frequency) it->second.frequency++;
      std::memcpy(out + i * dim_, s.arena.data() + it->second.offset,
                  sizeof(float) * dim_);
    }
  }

  void update(const int64_t* keys, int64_t n, const float* values,
              int64_t version) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t key = keys[i];
      Shard& s = shard_for(key);
      std::lock_guard<std::mutex> g(s.mu);
      auto it = s.map.find(key);
      if (it == s.map.end()) it = insert_locked(s, key);
      std::memcpy(s.arena.data() + it->second.offset, values + i * dim_,
                  sizeof(float) * dim_);
      it->second.version = version;
    }
  }

  // row pointer for fused optimizers (shard must be locked by caller
  // via for_each_row).
  template <typename Fn>
  void for_each_key(const int64_t* keys, int64_t n, int64_t version, Fn&& fn) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t key = keys[i];
      Shard& s = shard_for(key);
      std::lock_guard<std::mutex> g(s.mu);
      auto it = s.map.find(key);
      if (it == s.map.end()) it = insert_locked(s, key);
      it->second.version = version;
      fn(i, s.arena.data() + it->second.offset);
    }
  }

  int64_t evict(uint32_t min_frequency, int64_t min_version) {
    int64_t removed = 0;
    std::vector<float> row(dim_);
    for (auto& s : shards_) {
      std::lock_guard<std::mutex> g(s.mu);
      for (auto it = s.map.begin(); it != s.map.end();) {
        bool low_freq =
            min_frequency > 0 && it->second.frequency < min_frequency;
        bool stale = min_version > 0 && it->second.version < min_version;
        if (low_freq || stale) {
          s.free_rows.push_back(it->second.offset);
          it = s.map.erase(it);
          ++removed;
        } else {
          ++it;
        }
      }
      if (disk_) {
        for (auto it = s.disk_index.begin();
             it != s.disk_index.end();) {
          uint32_t freq = 0;
          int64_t version = 0;
          if (!disk_->read_row(it->second, row.data(), &freq,
                               &version)) {
            ++it;
            continue;
          }
          bool low_freq = min_frequency > 0 && freq < min_frequency;
          bool stale = min_version > 0 && version < min_version;
          if (low_freq || stale) {
            disk_->release(it->second);
            it = s.disk_index.erase(it);
            ++removed;
          } else {
            ++it;
          }
        }
      }
    }
    return removed;
  }

  // Export entries with version >= since_version (0 = full export).
  int64_t export_entries(int64_t since_version, int64_t* keys_out,
                         float* values_out, uint32_t* freq_out,
                         int64_t* version_out, int64_t capacity) const {
    int64_t count = 0;
    std::vector<float> row(dim_);
    for (auto& s : shards_) {
      std::lock_guard<std::mutex> g(s.mu);
      for (const auto& [key, slot] : s.map) {
        if (slot.version < since_version) continue;
        if (count < capacity) {
          keys_out[count] = key;
          std::memcpy(values_out + count * dim_, s.arena.data() + slot.offset,
                      sizeof(float) * dim_);
          if (freq_out) freq_out[count] = slot.frequency;
          if (version_out) version_out[count] = slot.version;
        }
        ++count;  // keep counting so caller can size the buffer
      }
      // Checkpoints and reshard moves must carry the COLD tier too —
      // losing spilled rows on a PS move would silently forget
      // long-tail embeddings.
      if (disk_) {
        for (const auto& [key, off] : s.disk_index) {
          uint32_t freq = 0;
          int64_t version = 0;
          if (!disk_->read_row(off, row.data(), &freq, &version)) {
            continue;
          }
          if (version < since_version) continue;
          if (count < capacity) {
            keys_out[count] = key;
            std::memcpy(values_out + count * dim_, row.data(),
                        sizeof(float) * dim_);
            if (freq_out) freq_out[count] = freq;
            if (version_out) version_out[count] = version;
          }
          ++count;
        }
      }
    }
    return count;
  }

  void import_entries(const int64_t* keys, const float* values,
                      const uint32_t* freqs, const int64_t* versions,
                      int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t key = keys[i];
      Shard& s = shard_for(key);
      std::lock_guard<std::mutex> g(s.mu);
      auto it = s.map.find(key);
      if (it == s.map.end()) it = insert_locked(s, key);
      std::memcpy(s.arena.data() + it->second.offset, values + i * dim_,
                  sizeof(float) * dim_);
      if (freqs) it->second.frequency = freqs[i];
      if (versions) it->second.version = versions[i];
    }
  }

 private:
  struct Shard {
    mutable std::mutex mu;
    std::unordered_map<int64_t, Slot> map;
    std::vector<float> arena;
    std::vector<uint32_t> free_rows;
    // key -> record offset in the disk tier (cold rows)
    std::unordered_map<int64_t, uint64_t> disk_index;
    // clock hand for sampled spill-candidate selection
    size_t clock_bucket = 0;
  };

  Shard& shard_for(int64_t key) {
    uint64_t h = static_cast<uint64_t>(key) * 0x9E3779B97F4A7C15ULL;
    return shards_[(h >> 32) % shards_.size()];
  }

  // Spill the coldest of a SAMPLE of rows to disk (caller holds the
  // shard lock). Overflow policy = lowest frequency, then oldest
  // version — the reference's under/over-flow ordering. Sampling
  // (like the reference) keeps inserts O(1): a clock hand walks the
  // hash buckets, so a full-shard scan per insert never happens.
  void spill_coldest_locked(Shard& s) {
    if (!disk_ || s.map.empty()) return;
    constexpr int kSample = 8;
    auto coldest = s.map.end();
    int seen = 0;
    size_t nbuckets = s.map.bucket_count();
    size_t b = s.clock_bucket % nbuckets;
    for (size_t walked = 0; walked < nbuckets && seen < kSample;
         ++walked, b = (b + 1) % nbuckets) {
      for (auto it = s.map.begin(b); it != s.map.end(b); ++it) {
        auto mit = s.map.find(it->first);
        if (coldest == s.map.end() ||
            mit->second.frequency < coldest->second.frequency ||
            (mit->second.frequency == coldest->second.frequency &&
             mit->second.version < coldest->second.version)) {
          coldest = mit;
        }
        if (++seen >= kSample) break;
      }
    }
    s.clock_bucket = (b + 1) % nbuckets;
    if (coldest == s.map.end()) return;
    uint64_t off = disk_->write_row(
        s.arena.data() + coldest->second.offset,
        coldest->second.frequency, coldest->second.version);
    if (off == DiskTier::kBadOffset) {
      return;  // disk unwritable: keep the row in RAM (soft overflow)
    }
    s.disk_index[coldest->first] = off;
    s.free_rows.push_back(coldest->second.offset);
    s.map.erase(coldest);
  }

  std::unordered_map<int64_t, Slot>::iterator insert_locked(Shard& s,
                                                            int64_t key) {
    if (disk_ &&
        static_cast<int64_t>(s.map.size()) >= per_shard_budget_) {
      spill_coldest_locked(s);
    }
    uint32_t offset;
    if (!s.free_rows.empty()) {
      offset = s.free_rows.back();
      s.free_rows.pop_back();
    } else {
      offset = static_cast<uint32_t>(s.arena.size());
      s.arena.resize(s.arena.size() + dim_);
    }
    // Promotion: a spilled key re-enters RAM with its persisted row,
    // frequency and version — not a fresh init.
    if (disk_) {
      auto dit = s.disk_index.find(key);
      if (dit != s.disk_index.end()) {
        uint32_t freq = 0;
        int64_t version = 0;
        bool read_ok = disk_->read_row(
            dit->second, s.arena.data() + offset, &freq, &version);
        // successful or not, the disk entry is consumed — a stale
        // index entry would double-count the key and resurrect old
        // state after an evict
        disk_->release(dit->second);
        s.disk_index.erase(dit);
        if (read_ok) {
          auto [it, ok] = s.map.emplace(key, Slot{offset, freq, version});
          return it;
        }
      }
    }
    init_row(key, s.arena.data() + offset);
    auto [it, ok] = s.map.emplace(key, Slot{offset, 0, 0});
    return it;
  }

  int dim_;
  uint64_t seed_;
  float init_scale_;
  int init_mode_;
  mutable std::vector<Shard> shards_;
  DiskTier* disk_ = nullptr;
  int64_t per_shard_budget_ = 0;
};

}  // namespace

extern "C" {

void* kv_create(int dim, uint64_t seed, int num_shards, float init_scale,
                int init_mode) {
  return new KvStore(dim, seed, num_shards > 0 ? num_shards : 16, init_scale,
                     init_mode);
}

void kv_destroy(void* h) { delete static_cast<KvStore*>(h); }

int64_t kv_size(void* h) { return static_cast<KvStore*>(h)->size(); }

int kv_set_disk_tier(void* h, const char* path, int64_t max_ram_rows) {
  return static_cast<KvStore*>(h)->set_disk_tier(path, max_ram_rows)
             ? 0
             : -1;
}

int64_t kv_ram_size(void* h) {
  return static_cast<KvStore*>(h)->ram_size();
}

int64_t kv_disk_size(void* h) {
  return static_cast<KvStore*>(h)->disk_size();
}

int kv_dim(void* h) { return static_cast<KvStore*>(h)->dim(); }

void kv_gather_or_insert(void* h, const int64_t* keys, int64_t n, float* out) {
  static_cast<KvStore*>(h)->gather(keys, n, out, /*insert=*/true,
                                   /*count=*/true);
}

void kv_gather_or_zeros(void* h, const int64_t* keys, int64_t n, float* out) {
  static_cast<KvStore*>(h)->gather(keys, n, out, /*insert=*/false,
                                   /*count=*/false);
}

void kv_update(void* h, const int64_t* keys, int64_t n, const float* values,
               int64_t version) {
  static_cast<KvStore*>(h)->update(keys, n, values, version);
}

int64_t kv_evict(void* h, uint32_t min_frequency, int64_t min_version) {
  return static_cast<KvStore*>(h)->evict(min_frequency, min_version);
}

int64_t kv_export(void* h, int64_t since_version, int64_t* keys_out,
                  float* values_out, uint32_t* freq_out, int64_t* version_out,
                  int64_t capacity) {
  return static_cast<KvStore*>(h)->export_entries(
      since_version, keys_out, values_out, freq_out, version_out, capacity);
}

void kv_import(void* h, const int64_t* keys, const float* values,
               const uint32_t* freqs, const int64_t* versions, int64_t n) {
  static_cast<KvStore*>(h)->import_entries(keys, values, freqs, versions, n);
}

// ---- fused sparse optimizers (ref training_ops.cc) ----
// Each consumes unique keys with per-key gradient rows; slot stores
// (m/v/accum/...) are sibling KvStore instances so checkpoints carry
// optimizer state exactly like the reference's slot KvVariables.

void kv_sparse_apply_adagrad(void* param_h, void* accum_h,
                             const int64_t* keys, const float* grads,
                             int64_t n, float lr, float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* accum = static_cast<KvStore*>(accum_h);
  int dim = param->dim();
  std::vector<float> acc_row(dim);
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    accum->for_each_key(&key, 1, step, [&](int64_t, float* a) {
      for (int d = 0; d < dim; ++d) {
        a[d] += g[d] * g[d];
        p[d] -= lr * g[d] / (std::sqrt(a[d]) + eps);
      }
    });
  });
}

void kv_sparse_apply_adam(void* param_h, void* m_h, void* v_h,
                          const int64_t* keys, const float* grads, int64_t n,
                          float lr, float beta1, float beta2, float eps,
                          int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          v[d] = beta2 * v[d] + (1.0f - beta2) * g[d] * g[d];
          p[d] -= lr * (m[d] / bc1) / (std::sqrt(v[d] / bc2) + eps);
        }
      });
    });
  });
}

void kv_sparse_apply_ftrl(void* param_h, void* accum_h, void* linear_h,
                          const int64_t* keys, const float* grads, int64_t n,
                          float lr, float l1, float l2, float lr_power,
                          int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* accum = static_cast<KvStore*>(accum_h);
  auto* linear = static_cast<KvStore*>(linear_h);
  int dim = param->dim();
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    accum->for_each_key(&key, 1, step, [&](int64_t, float* a) {
      linear->for_each_key(&key, 1, step, [&](int64_t, float* l) {
        for (int d = 0; d < dim; ++d) {
          float new_a = a[d] + g[d] * g[d];
          float sigma =
              (std::pow(new_a, -lr_power) - std::pow(a[d], -lr_power)) / lr;
          l[d] += g[d] - sigma * p[d];
          a[d] = new_a;
          float quad = std::pow(new_a, -lr_power) / lr + 2.0f * l2;
          float sign = l[d] < 0 ? -1.0f : 1.0f;
          if (std::fabs(l[d]) > l1) {
            p[d] = -(l[d] - sign * l1) / quad;
          } else {
            p[d] = 0.0f;
          }
        }
      });
    });
  });
}

// Group Adam with group lasso (ref training_ops.cc:1065
// KvVariableGroupSparseApplyAdamV2 / python group_adam.py): Adam
// moments feed an FTRL-style linear accumulator; the whole embedding
// row is soft-thresholded by the L21 group norm — rows whose
// shrunk-linear norm falls under l21*sqrt(dim) collapse to exactly
// zero (the reference blacklists the key; zeroing is the storewise
// equivalent — the row re-learns from zero if it comes back).
void kv_sparse_apply_group_adam(void* param_h, void* accum_h, void* linear_h,
                                void* m_h, void* v_h, const int64_t* keys,
                                const float* grads, int64_t n, float lr,
                                float beta1, float beta2, float eps, float l1,
                                float l2, float l21, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* accum = static_cast<KvStore*>(accum_h);
  auto* linear = static_cast<KvStore*>(linear_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float b1p = std::pow(beta1, static_cast<float>(step));
  float b2p = std::pow(beta2, static_cast<float>(step));
  float eps_adj = eps / std::sqrt(1.0f - b2p);
  float l21_norm = l21 * std::sqrt(static_cast<float>(dim));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    accum->for_each_key(&key, 1, step, [&](int64_t, float* a) {
      linear->for_each_key(&key, 1, step, [&](int64_t, float* l) {
        mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
          vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
            float norm_sq = 0.0f;
            for (int d = 0; d < dim; ++d) {
              m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
              v[d] = beta2 * v[d] + (1.0f - beta2) * g[d] * g[d];
              float new_a = v[d] / (1.0f - b2p);
              float delta = std::sqrt(new_a) - std::sqrt(a[d]);
              if (beta1 <= b1p) delta += eps_adj;  // first step
              l[d] += m[d] / (1.0f - b1p) - delta / lr * p[d];
              a[d] = new_a;
              float adj = std::fmin(std::fmax(l[d], -l1), l1);
              float l1l = adj - l[d];
              norm_sq += l1l * l1l;
            }
            float norm = std::sqrt(norm_sq);
            if (norm > l21_norm) {
              float scale = 1.0f - l21_norm / norm;
              for (int d = 0; d < dim; ++d) {
                float adj = std::fmin(std::fmax(l[d], -l1), l1);
                float l1l = adj - l[d];
                float y =
                    (std::sqrt(a[d]) + eps_adj) / lr + 2.0f * l2;
                p[d] = l1l * scale / y;
              }
            } else {
              std::memset(p, 0, sizeof(float) * dim);
            }
          });
        });
      });
    });
  });
}

// Group FTRL with group lasso + optional l2 shrinkage (ref
// training_ops.cc:597 KvVariableSparseGroupSparseApplyFtrlV2 /
// python sparse_group_ftrl.py). Same L21 whole-row threshold.
void kv_sparse_apply_group_ftrl(void* param_h, void* accum_h, void* linear_h,
                                const int64_t* keys, const float* grads,
                                int64_t n, float lr, float l1, float l2,
                                float l21, float lr_power, float l2_shrinkage,
                                int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* accum = static_cast<KvStore*>(accum_h);
  auto* linear = static_cast<KvStore*>(linear_h);
  int dim = param->dim();
  float l21_norm = l21 * std::sqrt(static_cast<float>(dim));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    accum->for_each_key(&key, 1, step, [&](int64_t, float* a) {
      linear->for_each_key(&key, 1, step, [&](int64_t, float* l) {
        float norm_sq = 0.0f;
        std::vector<float> new_accum(dim);
        for (int d = 0; d < dim; ++d) {
          float gu = g[d] + 2.0f * l2_shrinkage * p[d];
          new_accum[d] = a[d] + gu * gu;
          float sigma =
              (std::pow(new_accum[d], -lr_power) -
               std::pow(a[d], -lr_power)) /
              lr;
          l[d] += gu - sigma * p[d];
          a[d] = new_accum[d];
          float adj = std::fmin(std::fmax(l[d], -l1), l1);
          float l1l = adj - l[d];
          norm_sq += l1l * l1l;
        }
        float norm = std::sqrt(norm_sq);
        if (norm > l21_norm) {
          float scale = 1.0f - l21_norm / norm;
          for (int d = 0; d < dim; ++d) {
            float adj = std::fmin(std::fmax(l[d], -l1), l1);
            float l1l = adj - l[d];
            float y = std::pow(a[d], -lr_power) / lr + 2.0f * l2;
            p[d] = l1l * scale / y;
          }
        } else {
          std::memset(p, 0, sizeof(float) * dim);
        }
      });
    });
  });
}

// LAMB (You et al. 2020) on sparse rows: per-ROW trust ratio — the
// layerwise norm of the dense formulation becomes the embedding-row
// norm, which is the natural unit for a KvVariable.
void kv_sparse_apply_lamb(void* param_h, void* m_h, void* v_h,
                          const int64_t* keys, const float* grads, int64_t n,
                          float lr, float beta1, float beta2, float eps,
                          float weight_decay, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  std::vector<float> u(dim);
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        float p_norm_sq = 0.0f, u_norm_sq = 0.0f;
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          v[d] = beta2 * v[d] + (1.0f - beta2) * g[d] * g[d];
          u[d] = (m[d] / bc1) / (std::sqrt(v[d] / bc2) + eps) +
                 weight_decay * p[d];
          p_norm_sq += p[d] * p[d];
          u_norm_sq += u[d] * u[d];
        }
        float p_norm = std::sqrt(p_norm_sq);
        float u_norm = std::sqrt(u_norm_sq);
        float ratio =
            (p_norm > 0.0f && u_norm > 0.0f) ? p_norm / u_norm : 1.0f;
        for (int d = 0; d < dim; ++d) p[d] -= lr * ratio * u[d];
      });
    });
  });
}

// AdaBelief (Zhuang et al. 2020): second moment tracks the variance
// of the gradient around its EMA instead of the raw second moment.
void kv_sparse_apply_adabelief(void* param_h, void* m_h, void* s_h,
                               const int64_t* keys, const float* grads,
                               int64_t n, float lr, float beta1, float beta2,
                               float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* sstore = static_cast<KvStore*>(s_h);
  int dim = param->dim();
  float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      sstore->for_each_key(&key, 1, step, [&](int64_t, float* s) {
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          float diff = g[d] - m[d];
          s[d] = beta2 * s[d] + (1.0f - beta2) * diff * diff + eps;
          p[d] -= lr * (m[d] / bc1) / (std::sqrt(s[d] / bc2) + eps);
        }
      });
    });
  });
}

// AMSGrad (Reddi et al. 2018, ref training_ops.cc AMSGrad variants):
// Adam whose denominator uses the running MAX of the second moment,
// so the effective step size never grows back after a large gradient.
void kv_sparse_apply_amsgrad(void* param_h, void* m_h, void* v_h,
                             void* vhat_h, const int64_t* keys,
                             const float* grads, int64_t n, float lr,
                             float beta1, float beta2, float eps,
                             int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  auto* vhatstore = static_cast<KvStore*>(vhat_h);
  int dim = param->dim();
  float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        vhatstore->for_each_key(&key, 1, step, [&](int64_t, float* vh) {
          for (int d = 0; d < dim; ++d) {
            m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
            v[d] = beta2 * v[d] + (1.0f - beta2) * g[d] * g[d];
            vh[d] = std::max(vh[d], v[d]);
            p[d] -= lr * (m[d] / bc1) / (std::sqrt(vh[d] / bc2) + eps);
          }
        });
      });
    });
  });
}

// Rectified Adam (Liu et al. 2020, ref training_ops.cc RectifiedAdam):
// while the variance estimate's effective sample size rho_t is too
// small to be trusted (<= 4), take unadapted momentum-SGD steps;
// afterwards scale the adaptive step by the rectification ratio r_t.
void kv_sparse_apply_radam(void* param_h, void* m_h, void* v_h,
                           const int64_t* keys, const float* grads,
                           int64_t n, float lr, float beta1, float beta2,
                           float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float t = static_cast<float>(step);
  float bc1 = 1.0f - std::pow(beta1, t);
  float bc2 = 1.0f - std::pow(beta2, t);
  float rho_inf = 2.0f / (1.0f - beta2) - 1.0f;
  float beta2_t = std::pow(beta2, t);
  float rho_t = rho_inf - 2.0f * t * beta2_t / (1.0f - beta2_t);
  bool rectify = rho_t > 4.0f;
  float r_t = 1.0f;
  if (rectify) {
    r_t = std::sqrt(((rho_t - 4.0f) * (rho_t - 2.0f) * rho_inf) /
                    ((rho_inf - 4.0f) * (rho_inf - 2.0f) * rho_t));
  }
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          v[d] = beta2 * v[d] + (1.0f - beta2) * g[d] * g[d];
          float mhat = m[d] / bc1;
          if (rectify) {
            p[d] -= lr * r_t * mhat / (std::sqrt(v[d] / bc2) + eps);
          } else {
            p[d] -= lr * mhat;
          }
        }
      });
    });
  });
}

// Adadelta (Zeiler 2012, ref training_ops.cc Adadelta): step size
// self-tunes from the ratio of accumulated update and gradient RMS —
// no global learning-rate sensitivity (lr is the usual final scale).
void kv_sparse_apply_adadelta(void* param_h, void* accum_h,
                              void* accum_update_h, const int64_t* keys,
                              const float* grads, int64_t n, float lr,
                              float rho, float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* accum = static_cast<KvStore*>(accum_h);
  auto* accum_up = static_cast<KvStore*>(accum_update_h);
  int dim = param->dim();
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    accum->for_each_key(&key, 1, step, [&](int64_t, float* a) {
      accum_up->for_each_key(&key, 1, step, [&](int64_t, float* au) {
        for (int d = 0; d < dim; ++d) {
          a[d] = rho * a[d] + (1.0f - rho) * g[d] * g[d];
          float update = std::sqrt(au[d] + eps) /
                         std::sqrt(a[d] + eps) * g[d];
          au[d] = rho * au[d] + (1.0f - rho) * update * update;
          p[d] -= lr * update;
        }
      });
    });
  });
}

// AdaHessian (Yao et al. 2021, ref training_ops.cc AdaHessian): the
// second moment tracks the (Hutchinson-estimated, caller-supplied)
// Hessian diagonal instead of the squared gradient; hessian_power
// interpolates between Adam-like (0) and full Newton-ish (1) scaling.
void kv_sparse_apply_adahessian(void* param_h, void* m_h, void* v_h,
                                const int64_t* keys, const float* grads,
                                const float* hessian, int64_t n, float lr,
                                float beta1, float beta2, float eps,
                                float hessian_power, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    const float* h = hessian + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          v[d] = beta2 * v[d] + (1.0f - beta2) * h[d] * h[d];
          float denom =
              std::pow(std::sqrt(v[d] / bc2), hessian_power) + eps;
          p[d] -= lr * (m[d] / bc1) / denom;
        }
      });
    });
  });
}

// RMSProp (Tieleman & Hinton), torch conventions throughout: eps
// OUTSIDE the sqrt, momentum buffer holds the UNSCALED step
// (buf = momentum*buf + g/denom; p -= lr*buf) so a changing lr
// schedule applies the current lr to the whole buffer. mom_h may be
// null when momentum == 0 — no second slot store is allocated.
void kv_sparse_apply_rmsprop(void* param_h, void* ms_h, void* mom_h,
                             const int64_t* keys, const float* grads,
                             int64_t n, float lr, float rho,
                             float momentum, float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* msstore = static_cast<KvStore*>(ms_h);
  auto* momstore = static_cast<KvStore*>(mom_h);
  int dim = param->dim();
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    msstore->for_each_key(&key, 1, step, [&](int64_t, float* ms) {
      if (momstore == nullptr) {
        for (int d = 0; d < dim; ++d) {
          ms[d] = rho * ms[d] + (1.0f - rho) * g[d] * g[d];
          p[d] -= lr * g[d] / (std::sqrt(ms[d]) + eps);
        }
        return;
      }
      momstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
        for (int d = 0; d < dim; ++d) {
          ms[d] = rho * ms[d] + (1.0f - rho) * g[d] * g[d];
          m[d] = momentum * m[d] + g[d] / (std::sqrt(ms[d]) + eps);
          p[d] -= lr * m[d];
        }
      });
    });
  });
}

// Adamax (Kingma & Ba 2015 §7.1): infinity-norm second moment —
// u = max(beta2*u, |g|); no bias correction needed on u.
void kv_sparse_apply_adamax(void* param_h, void* m_h, void* u_h,
                            const int64_t* keys, const float* grads,
                            int64_t n, float lr, float beta1,
                            float beta2, float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* ustore = static_cast<KvStore*>(u_h);
  int dim = param->dim();
  float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      ustore->for_each_key(&key, 1, step, [&](int64_t, float* u) {
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          u[d] = std::max(beta2 * u[d], std::fabs(g[d]));
          p[d] -= lr * (m[d] / bc1) / (u[d] + eps);
        }
      });
    });
  });
}

// Nadam (Dozat 2016): Nesterov-accelerated Adam — the update mixes
// the bias-corrected momentum with the current gradient's own
// bias-corrected contribution.
void kv_sparse_apply_nadam(void* param_h, void* m_h, void* v_h,
                           const int64_t* keys, const float* grads,
                           int64_t n, float lr, float beta1,
                           float beta2, float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float t = static_cast<float>(step);
  float bc1 = 1.0f - std::pow(beta1, t);
  float bc1_next = 1.0f - std::pow(beta1, t + 1.0f);
  float bc2 = 1.0f - std::pow(beta2, t);
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          v[d] = beta2 * v[d] + (1.0f - beta2) * g[d] * g[d];
          float mhat = beta1 * m[d] / bc1_next +
                       (1.0f - beta1) * g[d] / bc1;
          p[d] -= lr * mhat / (std::sqrt(v[d] / bc2) + eps);
        }
      });
    });
  });
}

// Plain sparse gradient descent (ref: tfplus
// kv_variable/python/training/gradient_descent.py over the
// KvVariableSparseApplyGradientDescent kernel) — no slots; the
// simplest member of the fused-apply family and the baseline the
// adaptive ones are measured against.
void kv_sparse_apply_sgd(void* param_h, const int64_t* keys,
                         const float* grads, int64_t n, float lr,
                         int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  int dim = param->dim();
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    for (int d = 0; d < dim; ++d) p[d] -= lr * g[d];
  });
}

void kv_sparse_apply_momentum(void* param_h, void* mom_h, const int64_t* keys,
                              const float* grads, int64_t n, float lr,
                              float momentum, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(mom_h);
  int dim = param->dim();
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      for (int d = 0; d < dim; ++d) {
        m[d] = momentum * m[d] + g[d];
        p[d] -= lr * m[d];
      }
    });
  });
}

// AdaDQH (Ant's adaptive quasi-Hessian family; published as AGD,
// NeurIPS'23 — dense twin optim/agd.py): the difference of successive
// bias-corrected momenta approximates the Hessian diagonal, and the
// denominator max(sqrt(v_hat), eps) auto-switches each coordinate
// between the adaptive and SGD-with-momentum regimes. Restated from
// the published update rule (ref registrations:
// tfplus/kv_variable/ops/training_ops.cc ApplyAdaDQH /
// KvVariableSparseApplyAdaDQH):
//   m_t   = b1 m + (1-b1) g
//   u_t   = m_t/(1-b1^t) - m_{t-1}/(1-b1^{t-1})      (u_1 = m_1/bc1)
//   v_t   = b2 v + (1-b2) u_t^2
//   p    -= lr * (m_t/(1-b1^t)) / max(sqrt(v_t/(1-b2^t)), eps)
void kv_sparse_apply_adadqh(void* param_h, void* m_h, void* v_h,
                            const int64_t* keys, const float* grads,
                            int64_t n, float lr, float beta1, float beta2,
                            float eps, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float t = static_cast<float>(step);
  float bc1 = 1.0f - std::pow(beta1, t);
  float bc2 = 1.0f - std::pow(beta2, t);
  float bc1_old = step > 1 ? 1.0f - std::pow(beta1, t - 1.0f) : 1.0f;
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        for (int d = 0; d < dim; ++d) {
          float m_old_hat = m[d] / bc1_old;
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          float u = m[d] / bc1 - m_old_hat;
          v[d] = beta2 * v[d] + (1.0f - beta2) * u * u;
          p[d] -= lr * (m[d] / bc1) /
                  std::fmax(std::sqrt(v[d] / bc2), eps);
        }
      });
    });
  });
}

// Group AdaDQH with group lasso (ref KvVariableGroupSparseApplyAdaDQHV2):
// the AdaDQH moments feed an FTRL-proximal linear accumulator whose
// per-step "sigma" is the growth of the eps-floored RMS denominator;
// l1/l2/l21 arrive in loss units and are scaled by lr (the V2
// convention), and rows whose L21-shrunk linear norm falls below
// l21*lr*sqrt(dim) collapse to exact zeros (our storewise equivalent
// of the reference's key blacklist).
void kv_sparse_apply_group_adadqh(void* param_h, void* linear_h, void* m_h,
                                  void* v_h, const int64_t* keys,
                                  const float* grads, int64_t n, float lr,
                                  float beta1, float beta2, float eps,
                                  float l1, float l2, float l21,
                                  int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* linear = static_cast<KvStore*>(linear_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float t = static_cast<float>(step);
  float b1p = std::pow(beta1, t);
  float b2p = std::pow(beta2, t);
  float bc1 = 1.0f - b1p;
  float bc1_old = step > 1 ? 1.0f - std::pow(beta1, t - 1.0f) : 1.0f;
  float l1s = l1 * lr, l2s = l2 * lr, l21s = l21 * lr;
  float alpha = lr * std::sqrt(1.0f - b2p) / bc1;
  float eps_adj = eps * std::sqrt(1.0f - b2p);
  // the PREVIOUS step's eps floor — sigma must measure denominator
  // growth between consecutive steps, not against a moving floor
  // (b2p/beta2 = beta2^(t-1); at t=1 this is 1, floor 0)
  float last_eps_adj = eps * std::sqrt(1.0f - b2p / beta2);
  float l21_norm = l21s * std::sqrt(static_cast<float>(dim));
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    int64_t key = keys[i];
    linear->for_each_key(&key, 1, step, [&](int64_t, float* l) {
      mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
        vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
          float norm_sq = 0.0f;
          for (int d = 0; d < dim; ++d) {
            float m_old_hat = m[d] / bc1_old;
            float v_prev = v[d];
            m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
            float u = m[d] / bc1 - m_old_hat;
            v[d] = beta2 * v_prev + (1.0f - beta2) * u * u;
            float denom_new = std::fmax(std::sqrt(v[d]), eps_adj);
            float denom_old =
                std::fmax(std::sqrt(v_prev), last_eps_adj);
            l[d] += m[d] * alpha - (denom_new - denom_old) * p[d];
            float adj = std::fmin(std::fmax(l[d], -l1s), l1s);
            float l1l = adj - l[d];
            norm_sq += l1l * l1l;
          }
          float norm = std::sqrt(norm_sq);
          if (norm > l21_norm) {
            float scale = 1.0f - l21_norm / norm;
            for (int d = 0; d < dim; ++d) {
              float adj = std::fmin(std::fmax(l[d], -l1s), l1s);
              float l1l = adj - l[d];
              float y =
                  std::fmax(std::sqrt(v[d]), eps_adj) + 2.0f * l2s;
              p[d] = l1l * scale / y;
            }
          } else {
            std::memset(p, 0, sizeof(float) * dim);
          }
        });
      });
    });
  });
}

// LambHessian (ref ApplyLambHessian / KvVariableGroupSparseApplyLambHessian):
// LAMB's trust-ratio update with the second moment driven by a
// trainer-supplied Hutchinson Hessian-diagonal estimate instead of
// g^2 — layerwise normalization becomes per-ROW here, the natural
// unit for an embedding table.
void kv_sparse_apply_lamb_hessian(void* param_h, void* m_h, void* v_h,
                                  const int64_t* keys, const float* grads,
                                  const float* hessian, int64_t n, float lr,
                                  float beta1, float beta2, float eps,
                                  int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float t = static_cast<float>(step);
  float adjust = std::sqrt(1.0f - std::pow(beta2, t)) /
                 (1.0f - std::pow(beta1, t));
  std::vector<float> u(dim);
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    const float* hz = hessian + i * dim;
    int64_t key = keys[i];
    mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
      vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
        float p_norm_sq = 0.0f, u_norm_sq = 0.0f;
        for (int d = 0; d < dim; ++d) {
          m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
          v[d] = beta2 * v[d] + (1.0f - beta2) * hz[d] * hz[d];
          u[d] = (m[d] * adjust) / (std::sqrt(v[d]) + eps);
          p_norm_sq += p[d] * p[d];
          u_norm_sq += u[d] * u[d];
        }
        float p_norm = std::sqrt(p_norm_sq);
        float u_norm = std::sqrt(u_norm_sq);
        float ratio = (p_norm > 0.0f && u_norm > 0.0f)
                          ? p_norm / (u_norm + 1e-8f)
                          : 1.0f;
        for (int d = 0; d < dim; ++d) p[d] -= lr * ratio * u[d];
      });
    });
  });
}

// Group LambHessian: the trust-ratio-scaled curvature step feeds the
// same FTRL-proximal linear/group-lasso machinery as group_adam —
// sigma is the growth of the bias-corrected curvature RMS, and the
// y denominator carries 1/lr (this family's convention, unlike the
// V2 lr-scaled-regularizer convention above).
void kv_sparse_apply_group_lamb_hessian(
    void* param_h, void* accum_h, void* linear_h, void* m_h, void* v_h,
    const int64_t* keys, const float* grads, const float* hessian,
    int64_t n, float lr, float beta1, float beta2, float eps, float l1,
    float l2, float l21, int64_t step) {
  auto* param = static_cast<KvStore*>(param_h);
  auto* accum = static_cast<KvStore*>(accum_h);
  auto* linear = static_cast<KvStore*>(linear_h);
  auto* mstore = static_cast<KvStore*>(m_h);
  auto* vstore = static_cast<KvStore*>(v_h);
  int dim = param->dim();
  float t = static_cast<float>(step);
  float bc1 = 1.0f - std::pow(beta1, t);
  float bc2 = 1.0f - std::pow(beta2, t);
  float l21_norm = l21 * std::sqrt(static_cast<float>(dim));
  std::vector<float> r(dim);
  std::vector<float> new_accum(dim);
  param->for_each_key(keys, n, step, [&](int64_t i, float* p) {
    const float* g = grads + i * dim;
    const float* hz = hessian + i * dim;
    int64_t key = keys[i];
    accum->for_each_key(&key, 1, step, [&](int64_t, float* a) {
      linear->for_each_key(&key, 1, step, [&](int64_t, float* l) {
        mstore->for_each_key(&key, 1, step, [&](int64_t, float* m) {
          vstore->for_each_key(&key, 1, step, [&](int64_t, float* v) {
            float p_norm_sq = 0.0f, r_norm_sq = 0.0f;
            for (int d = 0; d < dim; ++d) {
              m[d] = beta1 * m[d] + (1.0f - beta1) * g[d];
              v[d] = beta2 * v[d] + (1.0f - beta2) * hz[d] * hz[d];
              new_accum[d] = v[d] / bc2;
              r[d] = (m[d] / bc1) /
                     (std::sqrt(new_accum[d]) + eps);
              p_norm_sq += p[d] * p[d];
              r_norm_sq += r[d] * r[d];
            }
            float p_norm = std::sqrt(p_norm_sq);
            float r_norm = std::sqrt(r_norm_sq);
            float ratio = (p_norm > 0.0f && r_norm > 0.0f)
                              ? p_norm / (r_norm + 1e-8f)
                              : 1.0f;
            float norm_sq = 0.0f;
            for (int d = 0; d < dim; ++d) {
              l[d] += (m[d] / bc1) * ratio -
                      (std::sqrt(new_accum[d]) - std::sqrt(a[d])) /
                          lr * p[d];
              a[d] = new_accum[d];
              float adj = std::fmin(std::fmax(l[d], -l1), l1);
              float l1l = adj - l[d];
              norm_sq += l1l * l1l;
            }
            float norm = std::sqrt(norm_sq);
            if (norm > l21_norm) {
              float scale = 1.0f - l21_norm / norm;
              for (int d = 0; d < dim; ++d) {
                float adj = std::fmin(std::fmax(l[d], -l1), l1);
                float l1l = adj - l[d];
                float y = (std::sqrt(a[d]) + eps) / lr + 2.0f * l2;
                p[d] = l1l * scale / y;
              }
            } else {
              std::memset(p, 0, sizeof(float) * dim);
            }
          });
        });
      });
    });
  });
}

}  // extern "C"
