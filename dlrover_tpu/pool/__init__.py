"""Multi-job pool control plane: one TPU pool, many tenants.

See docs/MULTI_JOB.md. The pool master owns a fixed slice inventory
and gang-schedules many jobs onto it with priority bands, FIFO within
a band, backfill, per-tenant quotas, and checkpoint-backed graceful
preemption; each placed job runs a full per-job JobMaster (node
table, rendezvous, shard ledger, kv store) behind one shared RPC
server, keyed by the ``_job`` envelope id.
"""

from dlrover_tpu.pool.master import (  # noqa: F401
    PoolJobContext,
    TPUPoolMaster,
    tracker_ckpt_probe,
)
from dlrover_tpu.pool.scheduler import (  # noqa: F401
    JobRuntime,
    PoolJobSpec,
    PoolJobState,
    PoolScheduler,
)
from dlrover_tpu.pool.slice_pool import (  # noqa: F401
    SlicePool,
    SliceSpec,
)
