"""Slice inventory + per-tenant quota accounting for the pool master.

The pool's unit of scheduling is a TPU *slice* (a rectangular ICI
domain: its hosts train together or not at all — the same invariant
``node_unit`` enforces inside one job's rendezvous, lifted to the
cluster level). A :class:`SlicePool` owns a fixed inventory of
slices, hands them to jobs **atomically** (a gang allocation either
gets every requested slice or nothing — no partial holds that could
deadlock two half-placed gangs against each other), and enforces
per-tenant quotas at allocation time.

Quota semantics: a tenant's quota caps its *placed* slices, never its
queue — an over-quota submission waits in the scheduler's queue (and
is skipped over, so it cannot starve other tenants) until the
tenant's own usage drops.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Union

from dlrover_tpu import obs
from dlrover_tpu.common.log import get_logger

logger = get_logger("slice_pool")

_SLICES = obs.gauge(
    "dlrover_pool_slices",
    "Slices in the pool by state (free / allocated)",
    ("state",),
)
_TENANT_SLICES = obs.gauge(
    "dlrover_pool_tenant_slices",
    "Slices currently allocated to each tenant's placed jobs",
    ("tenant",),
)


@dataclasses.dataclass(frozen=True)
class SliceSpec:
    """One schedulable TPU slice of the pool's inventory."""

    slice_id: int
    accelerator: str = "tpu"
    hosts: int = 1
    chips_per_host: int = 4

    @property
    def chips(self) -> int:
        return self.hosts * self.chips_per_host

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class SlicePool:
    """Thread-safe slice allocator with per-tenant quotas.

    ``slices`` is either an explicit inventory of :class:`SliceSpec`
    or an int (that many identical single-host slices — the hermetic
    drill/test shape). ``tenant_quotas`` maps tenant -> max placed
    slices; tenants absent from the map get ``default_quota``
    (None = unlimited).
    """

    def __init__(
        self,
        slices: Union[int, Sequence[SliceSpec]],
        tenant_quotas: Optional[Dict[str, int]] = None,
        default_quota: Optional[int] = None,
    ):
        if isinstance(slices, int):
            slices = [SliceSpec(slice_id=i) for i in range(slices)]
        self._slices: Dict[int, SliceSpec] = {
            s.slice_id: s for s in slices
        }
        if len(self._slices) != len(list(slices)):
            raise ValueError("duplicate slice_id in pool inventory")
        self._quotas = dict(tenant_quotas or {})
        self._default_quota = default_quota
        self._lock = threading.Lock()
        self._free: List[int] = sorted(self._slices)
        self._owner: Dict[int, str] = {}  # slice_id -> job_id
        self._job_slices: Dict[str, List[int]] = {}
        self._job_tenant: Dict[str, str] = {}
        # Every tenant that ever held a slice: a tenant whose usage
        # drops to zero must have its gauge SET to 0, not silently
        # stop being written (a stale series would report phantom
        # usage forever).
        self._gauge_tenants: set = set()
        # Optional CapacityLedger observing allocation lifecycles.
        # Notified OUTSIDE the pool lock (the ledger takes its own),
        # and best-effort: accounting must never fail an allocation.
        self.ledger = None
        self._update_gauges_locked()

    # -- inventory ----------------------------------------------------------

    @property
    def n_slices(self) -> int:
        return len(self._slices)

    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    def spec(self, slice_id: int) -> SliceSpec:
        return self._slices[slice_id]

    def specs(self) -> List[SliceSpec]:
        """The whole inventory, slice_id-ordered."""
        return [self._slices[sid] for sid in sorted(self._slices)]

    def slices_of(self, job_id: str) -> List[int]:
        with self._lock:
            return list(self._job_slices.get(job_id, ()))

    # -- quota --------------------------------------------------------------

    def quota_of(self, tenant: str) -> Optional[int]:
        return self._quotas.get(tenant, self._default_quota)

    def tenant_usage(self) -> Dict[str, int]:
        with self._lock:
            return self._tenant_usage_locked()

    def _tenant_usage_locked(self) -> Dict[str, int]:
        usage: Dict[str, int] = {}
        for job_id, sl in self._job_slices.items():
            tenant = self._job_tenant.get(job_id, "default")
            usage[tenant] = usage.get(tenant, 0) + len(sl)
        return usage

    def within_quota(self, tenant: str, n: int) -> bool:
        """Would placing ``n`` more slices keep ``tenant`` within its
        quota?"""
        quota = self.quota_of(tenant)
        if quota is None:
            return True
        with self._lock:
            used = self._tenant_usage_locked().get(tenant, 0)
        return used + n <= quota

    # -- allocation ---------------------------------------------------------

    def allocate(
        self, job_id: str, tenant: str, n: int
    ) -> Optional[List[int]]:
        """Atomically allocate ``n`` slices to ``job_id``. Returns
        the slice ids, or None when the pool cannot satisfy the whole
        gang (insufficient free slices, over quota, or the job
        already holds an allocation) — never a partial grant."""
        if n <= 0:
            return None
        quota = self.quota_of(tenant)
        with self._lock:
            if job_id in self._job_slices:
                logger.warning(
                    "job %s already holds %s; refusing re-allocation",
                    job_id, self._job_slices[job_id],
                )
                return None
            if len(self._free) < n:
                return None
            if quota is not None:
                used = self._tenant_usage_locked().get(tenant, 0)
                if used + n > quota:
                    return None
            granted = self._free[:n]
            self._free = self._free[n:]
            for sid in granted:
                self._owner[sid] = job_id
            self._job_slices[job_id] = granted
            self._job_tenant[job_id] = tenant
            self._update_gauges_locked()
        obs.event(
            "pool.allocate", job_id=job_id, tenant=tenant,
            slices=",".join(map(str, granted)),
        )
        self._notify_ledger("on_allocate", job_id, tenant, granted)
        return list(granted)

    def release(self, job_id: str) -> List[int]:
        """Return every slice ``job_id`` holds to the free set.
        Idempotent (an unknown/already-released job releases [])."""
        with self._lock:
            granted = self._job_slices.pop(job_id, [])
            self._job_tenant.pop(job_id, None)
            for sid in granted:
                self._owner.pop(sid, None)
            self._free = sorted(self._free + list(granted))
            self._update_gauges_locked()
        if granted:
            obs.event(
                "pool.release", job_id=job_id,
                slices=",".join(map(str, granted)),
            )
            self._notify_ledger("on_release", job_id, granted)
        return list(granted)

    # -- observability ------------------------------------------------------

    def _notify_ledger(self, hook: str, *args) -> None:
        ledger = self.ledger
        if ledger is None:
            return
        try:
            getattr(ledger, hook)(*args)
        except Exception:  # noqa: BLE001 — capacity accounting must
            # never fail an allocation or release
            logger.warning(
                "capacity ledger %s hook failed", hook, exc_info=True
            )

    def _update_gauges_locked(self) -> None:
        _SLICES.set(len(self._free), state="free")
        _SLICES.set(len(self._owner), state="allocated")
        usage = self._tenant_usage_locked()
        self._gauge_tenants |= set(usage)
        for tenant in self._gauge_tenants:
            _TENANT_SLICES.set(usage.get(tenant, 0), tenant=tenant)

    def snapshot(self) -> dict:
        with self._lock:
            usage = self._tenant_usage_locked()
            return {
                "total_slices": len(self._slices),
                "free_slices": list(self._free),
                "allocated": {
                    job: list(sl)
                    for job, sl in self._job_slices.items()
                },
                "tenants": {
                    tenant: {
                        "used": usage.get(tenant, 0),
                        "quota": self.quota_of(tenant),
                    }
                    for tenant in sorted(
                        set(usage)
                        | set(self._quotas)
                        | set(self._job_tenant.values())
                    )
                },
                "slices": {
                    str(sid): self._slices[sid].to_dict()
                    for sid in sorted(self._slices)
                },
            }
