"""Pool master: one RPC endpoint, many tenant jobs.

``TPUPoolMaster`` lifts the one-master-one-job control plane to a
shared TPU pool: it owns the slice inventory
(:class:`~dlrover_tpu.pool.slice_pool.SlicePool`), the gang scheduler
with checkpoint-backed preemption
(:class:`~dlrover_tpu.pool.scheduler.PoolScheduler`), one
:class:`~dlrover_tpu.common.comm.RpcServer` fronted by a
:class:`~dlrover_tpu.common.comm.JobRoutingDispatcher`, and one
shared :class:`~dlrover_tpu.obs.trace_store.TraceStore`.

Each *placed* job gets a full embedded
:class:`~dlrover_tpu.master.master.JobMaster` — its own node table,
rendezvous pair, shard ledger, kv store, health plane — registered
under its ``job_id`` on the routing dispatcher. Workers reach their
job's master through the pool's single address by stamping the job id
on the RPC envelope (``MasterClient(job_id=...)`` or the
``DLROVER_TPU_POOL_JOB_ID`` env); the single-job wire protocol is
otherwise unchanged.

**Preemption choreography** (the graceful path the scheduler drives
through :meth:`PoolJobContext.park`): the victim's workers each get a
``save_checkpoint`` then a ``stop_training`` action through their
job's normal heartbeat FIFO — the worker contract is *finish the
in-flight shard, report it, flash-checkpoint durably, exit* — and the
context confirms the park only once every worker reached a terminal
state AND the job's checkpoint probe reports the checkpoint durably
staged. Slices return to the pool strictly after that confirmation.
The job's ``JobMaster`` (and with it the shard ledger) stays alive
across the preemption, which is what makes the resume exactly-once:
completed shards stay completed, in-flight shards were reported
before the park, and the fresh workers of the resumed incarnation
simply continue the same ledger.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import JobRoutingDispatcher, RpcDispatcher, RpcServer
from dlrover_tpu.common.constants import (
    CheckpointConstant,
    EventAction,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs.capacity import CapacityLedger
from dlrover_tpu.obs.health import HealthMonitor
from dlrover_tpu.obs.timeseries import TimeSeriesStore
from dlrover_tpu.obs.trace_store import TraceStore
from dlrover_tpu.pool.scheduler import (
    JobRuntime,
    PoolJobSpec,
    PoolScheduler,
)
from dlrover_tpu.pool.slice_pool import SlicePool

logger = get_logger("pool_master")

# Env knobs (docs/MULTI_JOB.md knob table). Constructor arguments
# win; the env fills unset ones so a pool deployment is tunable
# without code.
PARK_TIMEOUT_ENV = "DLROVER_TPU_POOL_PARK_TIMEOUT_S"
QUOTAS_ENV = "DLROVER_TPU_POOL_QUOTAS"
DEFAULT_QUOTA_ENV = "DLROVER_TPU_POOL_DEFAULT_QUOTA"
WATCH_INTERVAL_ENV = "DLROVER_TPU_POOL_WATCH_INTERVAL_S"


def parse_quota_spec(spec: str) -> Dict[str, int]:
    """``"research=3,prod=8"`` -> {"research": 3, "prod": 8} (the
    DLROVER_TPU_POOL_QUOTAS format)."""
    out: Dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        tenant, _, n = part.partition("=")
        try:
            out[tenant.strip()] = int(n)
        except ValueError:
            raise ValueError(
                f"bad quota entry {part!r}: expected tenant=N"
            ) from None
    return out


def tracker_ckpt_probe(ckpt_dir: str) -> Callable[[], dict]:
    """Checkpoint-staging probe over the flash-checkpoint tracker
    file contract: the checkpoint is durably staged when
    ``<ckpt_dir>/latest_checkpointed_iteration.txt`` names a step.
    ``mtime`` rides along so the park choreography can distinguish a
    FRESH park-time write from a stale tracker left by an earlier
    park/periodic save — any tracker would otherwise vacuously
    satisfy "staged before release" forever after the job's first
    checkpoint."""

    def probe() -> dict:
        path = os.path.join(
            ckpt_dir, CheckpointConstant.TRACKER_FILE
        )
        try:
            with open(path) as f:
                step = int(f.read().strip() or -1)
            mtime = os.stat(path).st_mtime
        except (OSError, ValueError):
            return {
                "staged": False, "path": path, "step": -1,
                "mtime": 0.0,
            }
        return {
            "staged": step >= 0, "path": path, "step": step,
            "mtime": mtime,
        }

    return probe


class PoolJobContext(JobRuntime):
    """One admitted job inside the pool master process."""

    def __init__(
        self,
        pool_master: "TPUPoolMaster",
        spec: PoolJobSpec,
        worker_launcher: Optional[Callable] = None,
        ckpt_probe: Optional[Callable[[], dict]] = None,
        job_master_kwargs: Optional[dict] = None,
    ):
        self._pool = pool_master
        self.spec = spec
        self.worker_launcher = worker_launcher
        # None = the job declared no durable training state: safe to
        # park without a checkpoint, confirmed as staged-stateless.
        self.ckpt_probe = ckpt_probe
        self.job_master_kwargs = dict(job_master_kwargs or {})
        self.master = None  # JobMaster, built at first placement
        self.dispatcher: Optional[RpcDispatcher] = None
        self.slices: List[int] = []
        self._parking = threading.Event()
        self._closed = False
        # Completion guard: all_workers_done() is vacuously true right
        # after a (re)placement until the fresh incarnation's workers
        # register — completing then would retire a job that never
        # restarted. Set once a RUNNING worker is seen post-placement.
        self._workers_seen = False

    # -- JobRuntime ---------------------------------------------------------

    def _grant_hosts(self, slices: List[int]) -> int:
        return sum(
            self._pool.pool.spec(sid).hosts for sid in slices
        )

    def place(self, slices: List[int], resume: bool) -> None:
        self.slices = list(slices)
        # Order matters: the completion watcher may tick between
        # these lines — reset the workers-seen guard while _parking
        # still suppresses check_complete, or a stale True plus the
        # parked incarnation's all-terminal node table would complete
        # the job before its resume workers ever start.
        self._workers_seen = False
        self._parking.clear()
        hosts = self._grant_hosts(slices)
        if self.master is None:
            from dlrover_tpu.common.constants import (
                NodeEventType,
                NodeType,
            )
            from dlrover_tpu.master.master import JobMaster

            self.dispatcher = RpcDispatcher()
            kwargs = dict(self.job_master_kwargs)
            kwargs.setdefault("node_num", hosts)
            kwargs.setdefault(
                "min_nodes",
                max(
                    self._grant_min_hosts(), 1
                ) if self.spec.min_slices > 0 else 0,
            )
            self.master = JobMaster(
                job_id=self.spec.job_id,
                dispatcher=self.dispatcher,
                trace_store=self._pool.traces,
                pool_grant=hosts,
                **kwargs,
            )

            # Event-driven, not polled: a short job's workers can
            # register AND finish entirely between two watcher
            # ticks — a poll for "alive workers" would miss the
            # incarnation and strand the job PLACED forever.
            def _on_node_event(node, event_type):
                if (
                    event_type == NodeEventType.CREATED
                    and node.type
                    in (NodeType.WORKER, NodeType.CHIEF)
                ):
                    self._workers_seen = True
                    # Capacity: a resumed incarnation's slices leave
                    # `restoring` once its workers re-register (a
                    # fresh placement is already `allocated`: no-op).
                    try:
                        self._pool.capacity.job_ready(
                            self.spec.job_id
                        )
                    except Exception:  # noqa: BLE001
                        pass

            self.master.job_manager.add_listener(_on_node_event)
            self._pool.router.register_job(
                self.spec.job_id, self.dispatcher
            )
            self.master.prepare()
        else:
            # Elastic re-admission: same master, same ledger, a
            # possibly smaller grant.
            self.master.job_manager.pool_grant = hosts
        if self.worker_launcher is not None:
            self.worker_launcher(
                self.spec.job_id, self._pool.addr, list(slices), resume
            )

    def _grant_min_hosts(self) -> int:
        """The SMALLEST host count any min_slices-sized grant could
        carry: on a heterogeneous pool, an elastic resume may land on
        the small slices — a min_nodes derived from the big ones
        would strand the resumed incarnation below its own
        rendezvous floor forever."""
        specs = sorted(
            self._pool.pool.specs(), key=lambda s: s.hosts
        )
        return sum(
            s.hosts for s in specs[: self.spec.min_slices]
        )

    def park(self, on_parked: Callable[[dict], None]) -> None:
        """Graceful eviction, asynchronously: deliver
        save_checkpoint + stop_training through each worker's
        heartbeat FIFO, wait for every worker to reach a terminal
        state, then wait for the checkpoint probe to confirm durable
        staging — only then confirm the park."""
        if self.master is None:
            on_parked({"staged": True, "note": "never placed"})
            return
        self._parking.set()
        # Baseline BEFORE any park action is delivered: the park's
        # checkpoint must be newer than whatever the tracker already
        # named, or a stale checkpoint from an earlier park/periodic
        # save would vacuously confirm the staging.
        base = self.ckpt_probe() if self.ckpt_probe else None
        servicer = self.master.servicer
        workers = self.master.job_manager.alive_workers(
            include_chief=True
        )
        for node in workers:
            servicer.push_action(
                node.id, EventAction.SAVE_CHECKPOINT.value
            )
            servicer.push_action(
                node.id, EventAction.STOP_TRAINING.value
            )
        obs.event(
            "pool.park_begin", job_id=self.spec.job_id,
            workers=len(workers),
        )

        deadline = time.monotonic() + max(
            self._pool.scheduler.park_timeout_s - 2.0, 1.0
        )

        def wait_and_confirm() -> None:
            jm = self.master.job_manager
            while time.monotonic() < deadline:
                if not jm.alive_workers(include_chief=True):
                    break
                time.sleep(0.05)
            info: dict
            if self.ckpt_probe is None:
                info = {"staged": True, "note": "stateless"}
            else:

                def fresh(p: dict) -> bool:
                    if not p.get("staged"):
                        return False
                    if base is None or not base.get("staged"):
                        return True  # no prior checkpoint to confuse
                    return (
                        p.get("step", -1) > base.get("step", -1)
                        or p.get("mtime", 0.0)
                        > base.get("mtime", 0.0)
                    )

                info = {"staged": False}
                while time.monotonic() < deadline:
                    info = self.ckpt_probe()
                    if fresh(info):
                        break
                    time.sleep(0.05)
                if not fresh(info):
                    info = dict(info)
                    info["staged"] = False
                    info.setdefault(
                        "error",
                        "tracker never advanced past the pre-park "
                        "checkpoint",
                    )
            still_alive = len(
                jm.alive_workers(include_chief=True)
            )
            if still_alive:
                # Workers ignored the park entirely: this is a
                # FORCED reclaim, not an unstaged-but-clean one —
                # the scheduler must hard-stop the incarnation
                # before its slices are reused.
                info = dict(info)
                info["staged"] = False
                info["forced"] = True
                info["error"] = (
                    f"{still_alive} worker(s) never parked"
                )
            on_parked(info)

        t = threading.Thread(
            target=wait_and_confirm,
            name=f"park-{self.spec.job_id}",
            daemon=True,
        )
        t.start()

    def stop(self) -> None:
        """Hard stop (forced reclaim / cancellation): push the stop
        action for polite workers, then retire the nodes through the
        ScalePlan seam — a runtime being force-stopped may be
        ignoring actions entirely (that is usually WHY it is being
        forced), and only pod deletion actually frees the hardware."""
        if self.master is None:
            return
        jm = self.master.job_manager
        workers = jm.alive_workers(include_chief=True)
        for node in workers:
            self.master.servicer.push_action(
                node.id, EventAction.STOP_TRAINING.value
            )
        for node in workers:
            jm.retire_node(node.id)

    # -- completion watching ------------------------------------------------

    def check_complete(self) -> bool:
        """True when the placed job's training fleet has finished
        (used by the pool's watcher; guarded against the vacuous
        window before the incarnation's workers register)."""
        if self.master is None or self._parking.is_set():
            return False
        if not self._workers_seen:
            return False
        return self.master.job_manager.all_workers_done()

    def close(self) -> None:
        """Tear down the embedded master (idempotent): called when
        the job reaches a terminal state and again defensively at
        pool shutdown. Without this, every finished job would leak
        its master's threads, its dispatcher registration, and its
        fleet hook into the process-global metrics registry."""
        if self._closed:
            return
        self._closed = True
        if self.master is not None:
            try:
                self.master.stop()
            except Exception:  # noqa: BLE001
                logger.exception(
                    "stopping job master %s failed", self.spec.job_id
                )
            self._pool.router.remove_job(self.spec.job_id)


class TPUPoolMaster:
    """The pool-level control plane (see module docstring)."""

    def __init__(
        self,
        slices,
        port: int = 0,
        tenant_quotas: Optional[Dict[str, int]] = None,
        default_quota: Optional[int] = None,
        park_timeout_s: Optional[float] = None,
        watch_interval: Optional[float] = None,
        worker_launcher: Optional[Callable] = None,
        job_master_defaults: Optional[dict] = None,
        metrics_port: Optional[int] = None,
        slos=None,
        brain=None,
    ):
        if tenant_quotas is None and os.getenv(QUOTAS_ENV, ""):
            tenant_quotas = parse_quota_spec(os.environ[QUOTAS_ENV])
        if default_quota is None and os.getenv(
            DEFAULT_QUOTA_ENV, ""
        ):
            default_quota = int(os.environ[DEFAULT_QUOTA_ENV])
        if park_timeout_s is None:
            park_timeout_s = float(
                os.getenv(PARK_TIMEOUT_ENV, "") or 120.0
            )
        if watch_interval is None:
            watch_interval = float(
                os.getenv(WATCH_INTERVAL_ENV, "") or 1.0
            )
        self.pool = SlicePool(
            slices,
            tenant_quotas=tenant_quotas,
            default_quota=default_quota,
        )
        self.traces = TraceStore()
        self.scheduler = PoolScheduler(
            self.pool,
            trace_sink=self.traces,
            park_timeout_s=park_timeout_s,
        )
        # Capacity accounting plane: the interval ledger observes the
        # pool's allocation lifecycle (via pool/scheduler hooks) and
        # the watcher tick joins in each placed job's goodput ratio
        # and serving latency percentiles; the SLO budget engine over
        # the same store turns per-tenant objectives into error
        # budgets with burn-rate alerting. ``slos`` is a list of
        # obs.SLOSpec (None = DLROVER_TPU_HEALTH_SLOS env, if set);
        # ``brain`` is any BrainService-shaped datastore.
        self.brain = brain
        self.timeseries = TimeSeriesStore()
        self.capacity = CapacityLedger(
            self.pool.specs(),
            timeseries=self.timeseries,
            brain=brain,
            job_name="pool",
        )
        self.pool.ledger = self.capacity
        # No monitor thread: SLO evaluation rides the watcher tick so
        # drills stay deterministic (tick_once -> evaluate_once).
        self.health = HealthMonitor(
            store=self.timeseries,
            brain=brain,
            job_name="pool",
            slos=slos,
            interval=watch_interval,
        )
        self.router = JobRoutingDispatcher()
        self._server = RpcServer(self.router, port=port)
        self._contexts: Dict[str, PoolJobContext] = {}
        self._ctx_lock = threading.Lock()
        # RPC-path defaults (submissions arriving over the wire have
        # no way to pass callables).
        self._default_launcher = worker_launcher
        self._job_master_defaults = dict(job_master_defaults or {})
        self._watch_interval = watch_interval
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        # Prometheus /metrics (the dlrover_pool_* series land in the
        # same process-global registry the job planes use); None =
        # RPC-only exposition via MetricsRequest.
        self._metrics_port = metrics_port
        self.metrics_server = None
        # Ring-evicted terminal jobs drop their contexts here.
        self.scheduler.on_job_evicted = self._on_job_evicted
        self._register_rpc()

    def _on_job_evicted(self, job_id: str) -> None:
        with self._ctx_lock:
            ctx = self._contexts.pop(job_id, None)
        if ctx is not None:
            ctx.close()

    # -- lifecycle ----------------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def addr(self) -> str:
        return self._server.addr

    def prepare(self) -> None:
        self._server.start()
        self._watcher = threading.Thread(
            target=self._watch_loop, name="pool-watcher", daemon=True
        )
        self._watcher.start()
        if self._metrics_port is not None:
            from dlrover_tpu.obs.exposition import MetricsHTTPServer

            self.metrics_server = MetricsHTTPServer(
                port=self._metrics_port
            )
            self.metrics_server.start()
        logger.info(
            "pool master serving %d slices on %s",
            self.pool.n_slices, self.addr,
        )

    def stop(self) -> None:
        self._stop.set()
        with self._ctx_lock:
            contexts = list(self._contexts.values())
        for ctx in contexts:
            ctx.close()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        self._server.stop(0)

    # -- submissions --------------------------------------------------------

    def submit(
        self,
        spec: PoolJobSpec,
        worker_launcher: Optional[Callable] = None,
        ckpt_probe: Optional[Callable[[], dict]] = None,
        job_master_kwargs: Optional[dict] = None,
    ) -> dict:
        """Admit one job (in-process API; the PoolSubmitRequest RPC
        routes here with the constructor defaults). Idempotent on
        job_id."""
        with self._ctx_lock:
            if spec.job_id in self._contexts:
                info = self.scheduler.job_info(spec.job_id) or {}
                return {
                    "state": info.get("state", ""),
                    "reason": "already submitted",
                    "trace_id": info.get("trace_id", ""),
                }
            ctx = PoolJobContext(
                self,
                spec,
                worker_launcher=(
                    worker_launcher or self._default_launcher
                ),
                ckpt_probe=ckpt_probe,
                job_master_kwargs={
                    **self._job_master_defaults,
                    **(job_master_kwargs or {}),
                },
            )
            self._contexts[spec.job_id] = ctx
        result = self.scheduler.submit(spec, ctx)
        if not result.get("state"):
            # Rejected outright (bad spec): drop the context again.
            with self._ctx_lock:
                self._contexts.pop(spec.job_id, None)
        return result

    def context(self, job_id: str) -> Optional[PoolJobContext]:
        with self._ctx_lock:
            return self._contexts.get(job_id)

    # -- completion watcher -------------------------------------------------

    def tick_once(self) -> None:
        """One completion-watch pass (drills tick deterministically;
        the background watcher calls this on ``watch_interval``)."""
        from dlrover_tpu.pool.scheduler import PoolJobState

        with self._ctx_lock:
            contexts = list(self._contexts.values())
        for ctx in contexts:
            info = self.scheduler.job_info(ctx.spec.job_id)
            if info is None:
                continue
            if info["state"] in PoolJobState.TERMINAL:
                # Reclaim the embedded master of a finished/cancelled
                # job (threads, dispatcher slot, registry hook); the
                # scheduler record stays for status/trace queries.
                ctx.close()
                continue
            if info["state"] != PoolJobState.PLACED:
                continue
            if ctx.check_complete():
                logger.info(
                    "job %s finished; returning %s to the pool",
                    ctx.spec.job_id, info["slices"],
                )
                self.scheduler.complete(ctx.spec.job_id)
        self.observe_capacity()

    def observe_capacity(self) -> None:
        """Join each placed job's telemetry into the capacity plane:
        the embedded JobMaster's goodput ratio (-> productive
        chip-seconds + ``tenant.goodput`` series) and its serving
        router's TTFT/TPOT p99s (-> ``tenant.ttft_p99_s`` /
        ``tenant.tpot_p99_s``), then one SLO budget evaluation.
        Rides the watcher tick; drills call it directly."""
        with self._ctx_lock:
            contexts = list(self._contexts.values())
        for ctx in contexts:
            jm = ctx.master
            if jm is None or not ctx.slices:
                continue
            tenant = ctx.spec.tenant
            job_id = ctx.spec.job_id
            goodput = getattr(jm, "goodput", None)
            if goodput is not None:
                try:
                    report = goodput.account()
                except Exception:  # noqa: BLE001
                    report = None
                if report is not None:
                    self.capacity.observe_goodput(
                        job_id, report.goodput_ratio
                    )
            serving = getattr(jm, "serving", None)
            if serving is not None:
                try:
                    ttft = serving.phase_p99(
                        "queue"
                    ) + serving.phase_p99("prefill")
                    tpot = serving.phase_p99("tpot")
                except Exception:  # noqa: BLE001
                    ttft = tpot = 0.0
                # Idle routers report 0 — recording that would count
                # as an SLO-compliant sample without any traffic.
                # Each signal lands twice: the per-job series (purged
                # when the job retires) and the tenant-level series
                # the SLO budget engine queries (the store matches on
                # the exact label set).
                if ttft > 0:
                    self.timeseries.record(
                        "tenant.ttft_p99_s", ttft,
                        tenant=tenant, job=job_id,
                    )
                    self.timeseries.record(
                        "tenant.ttft_p99_s", ttft, tenant=tenant
                    )
                if tpot > 0:
                    self.timeseries.record(
                        "tenant.tpot_p99_s", tpot,
                        tenant=tenant, job=job_id,
                    )
                    self.timeseries.record(
                        "tenant.tpot_p99_s", tpot, tenant=tenant
                    )
        try:
            self.health.evaluate_once()
        except Exception:  # noqa: BLE001
            logger.exception("pool SLO evaluation failed")

    def _watch_loop(self) -> None:
        while not self._stop.wait(self._watch_interval):
            try:
                self.tick_once()
            except Exception:  # noqa: BLE001
                logger.exception("pool watcher pass failed")

    # -- pool-level RPC surface ---------------------------------------------

    def _register_rpc(self) -> None:
        g = self.router.register_get
        g(msg.PoolSubmitRequest, self._rpc_submit)
        g(msg.PoolJobStatusRequest, self._rpc_status)
        g(msg.PoolQueryRequest, self._rpc_query)
        g(msg.CapacityQueryRequest, self._rpc_capacity)
        g(msg.TraceQueryRequest, self._rpc_traces)
        g(msg.MetricsRequest, self._rpc_metrics)

    def _rpc_submit(self, req: msg.PoolSubmitRequest):
        spec = PoolJobSpec(
            job_id=req.job_id,
            tenant=req.tenant or "default",
            priority=req.priority,
            n_slices=req.n_slices,
            min_slices=req.min_slices,
            queue=req.queue or "default",
        )
        result = self.submit(spec)
        return msg.PoolSubmitResponse(
            job_id=req.job_id,
            accepted=bool(result.get("state")),
            state=result.get("state", ""),
            reason=result.get("reason", ""),
            trace_id=result.get("trace_id", ""),
        )

    def _rpc_status(self, req: msg.PoolJobStatusRequest):
        info = self.scheduler.job_info(req.job_id)
        if info is None:
            return msg.PoolJobStatusResponse(
                job_id=req.job_id, known=False
            )
        return msg.PoolJobStatusResponse(
            job_id=req.job_id,
            known=True,
            state=info["state"],
            tenant=info["tenant"],
            priority=info["priority"],
            n_slices=info["n_slices"],
            slices=info["slices"],
            preemptions=info["preemptions"],
            trace_id=info["trace_id"],
            message=info["reason"],
        )

    def _rpc_query(self, req: msg.PoolQueryRequest):
        return msg.PoolQueryResponse(
            enabled=True, snapshot=self.scheduler.snapshot()
        )

    def _rpc_capacity(self, req: msg.CapacityQueryRequest):
        snapshot = self.capacity.snapshot()
        snapshot["slo"] = {"budgets": self.health.slo_snapshot()}
        return msg.CapacityQueryResponse(
            enabled=True, snapshot=snapshot
        )

    def _rpc_traces(self, req: msg.TraceQueryRequest):
        from dlrover_tpu.master.servicer import MAX_TRACE_QUERY

        limit = req.limit
        if not req.trace_id:
            limit = (
                min(limit, MAX_TRACE_QUERY)
                if limit > 0
                else MAX_TRACE_QUERY
            )
        return msg.TraceQueryResponse(
            enabled=True,
            traces=self.traces.query(
                trace_id=req.trace_id,
                subject=req.subject,
                limit=limit,
            ),
        )

    def _rpc_metrics(self, req: msg.MetricsRequest):
        return msg.MetricsResponse(
            text=obs.get_registry().render()
        )
