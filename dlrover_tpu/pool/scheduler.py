"""Gang scheduler + preemption engine for the multi-job pool.

One :class:`PoolScheduler` owns the queue of every job submitted to a
:class:`~dlrover_tpu.pool.slice_pool.SlicePool` and decides, on every
``schedule_once`` pass, which jobs run where:

* **Gang placement** — a job is placed only when its *whole* slice
  gang can be allocated atomically (``SlicePool.allocate`` is
  all-or-nothing), so two half-placed gangs can never deadlock each
  other holding partial grants.
* **Priority bands, FIFO within a band** — the queue orders by
  (priority desc, admission seq asc). Priorities are integer bands
  0..9 (higher wins), matching the ``priority`` field of the
  ElasticJob CRD.
* **Backfill** — when the head of the queue cannot be placed, a
  strictly LOWER-priority job further down that fits entirely in the
  current free slices is placed into the hole. Lower-priority only:
  the head can preempt it back the moment its gang becomes feasible,
  so backfill can delay the head by at most one graceful checkpoint —
  and a same-band job jumping the queue would break FIFO fairness.
* **Checkpoint-backed preemption** — when the head outranks running
  jobs, the engine evicts the cheapest victims (lowest priority
  first, youngest first within a band) through the *graceful* path:
  the victim's runtime parks its workers (CORDON-style: finish the
  in-flight shard, flash-checkpoint durably), and ONLY after the
  runtime confirms the checkpoint is staged are the victim's slices
  released. A parked job re-enters the queue at its original
  admission seq (it does not lose its FIFO place) and is re-admitted
  **elastically**: when capacity returns partially, it may resume
  with fewer slices (>= ``min_slices``), growing back later through
  its own master's elasticity.

The scheduler never talks to workers itself — it drives
:class:`JobRuntime` objects (the pool master's per-job contexts, or
test fakes) through three calls: ``place(slices, resume)``,
``park(on_parked)``, ``stop()``.

Every job's pool lifecycle is one distributed trace in the shared
:class:`~dlrover_tpu.obs.trace_store.TraceStore`; preemption spans
(park -> checkpoint staged -> release) are recorded in the
*demanding* job's trace — tagged with the victim's id as a subject —
so the whole queue -> preempt -> place -> resume story of one
capacity incident reads as a single timeline via ``query_traces``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.log import get_logger

logger = get_logger("pool_scheduler")

MAX_PRIORITY = 9
# Wait-time samples retained per band for the snapshot percentiles.
WAIT_SAMPLES_PER_BAND = 256
# Terminal (done/failed/cancelled) job records retained for status/
# snapshot queries — ring-bounded like every other retention surface
# in this repo (trace store, request ledger): a long-lived pool
# serving thousands of short jobs must not grow without bound.
MAX_TERMINAL_JOBS = 512

_QUEUE_DEPTH = obs.gauge(
    "dlrover_pool_queue_depth",
    "Jobs waiting for placement (queued + preempted), by priority "
    "band",
    ("band",),
)
_JOBS = obs.gauge(
    "dlrover_pool_jobs",
    "Pool jobs by lifecycle state",
    ("state",),
)
_PLACEMENT_SECONDS = obs.histogram(
    "dlrover_pool_placement_seconds",
    "Wall time from submission to first placement",
)
_WAIT_SECONDS = obs.histogram(
    "dlrover_pool_wait_seconds",
    "Wall time spent waiting before each placement (first placement "
    "and every elastic re-admission), by priority band",
    ("band",),
)
_PREEMPTIONS = obs.counter(
    "dlrover_pool_preemptions_total",
    "Jobs preempted by the pool scheduler, by reason (priority = "
    "clean graceful eviction for a higher band; unstaged = workers "
    "parked but the checkpoint never confirmed staging; forced = "
    "the graceful park timed out or failed and the slices were "
    "reclaimed with a hard stop)",
    ("reason",),
)
_QUOTA_DENIED = obs.counter(
    "dlrover_pool_quota_denied_total",
    "Placement attempts skipped because the tenant was at quota",
    ("tenant",),
)
_BACKFILLS = obs.counter(
    "dlrover_pool_backfills_total",
    "Lower-priority jobs placed into holes ahead of a blocked "
    "queue head",
)


class PoolJobState:
    QUEUED = "queued"
    PLACED = "placed"
    PREEMPTING = "preempting"  # graceful park in flight
    PREEMPTED = "preempted"  # parked; waiting for re-admission
    DONE = "done"
    FAILED = "failed"
    CANCELLED = "cancelled"

    WAITING = (QUEUED, PREEMPTED)
    RUNNING = (PLACED, PREEMPTING)
    TERMINAL = (DONE, FAILED, CANCELLED)


@dataclasses.dataclass(frozen=True)
class PoolJobSpec:
    job_id: str
    tenant: str = "default"
    priority: int = 0
    n_slices: int = 1
    # Elastic floor for RE-admission after a preemption: 0 = not
    # elastic, the full gang is required to resume too.
    min_slices: int = 0
    queue: str = "default"


class JobRuntime:
    """What the scheduler needs from a job's execution side. The pool
    master's per-job context implements this over an embedded
    JobMaster; tests use in-memory fakes."""

    def place(self, slices: List[int], resume: bool) -> None:
        """Start (or elastically resume, ``resume=True``) the job on
        these slices."""
        raise NotImplementedError

    def park(self, on_parked: Callable[[dict], None]) -> None:
        """Gracefully stop the job: finish in-flight shards, flash-
        checkpoint durably, then call ``on_parked({"staged": bool,
        "path": ..., "step": ...})``. The scheduler releases the
        job's slices only after this callback — checkpoint staging
        strictly precedes slice release."""
        raise NotImplementedError

    def stop(self) -> None:
        """Hard stop (cancellation); no checkpoint contract."""
        raise NotImplementedError


class _Job:
    __slots__ = (
        "spec", "runtime", "state", "seq", "trace_id",
        "submit_wall", "submit_mono", "wait_since_mono",
        "wait_since_wall", "placed_mono", "first_placed",
        "slices", "preemptions", "preempt_trace", "park_started_wall",
        "reason", "quota_logged", "done_wall",
    )

    def __init__(self, spec: PoolJobSpec, runtime: JobRuntime,
                 seq: int, trace_id: str):
        self.spec = spec
        self.runtime = runtime
        self.state = PoolJobState.QUEUED
        self.seq = seq
        self.trace_id = trace_id
        self.submit_wall = time.time()
        self.submit_mono = time.monotonic()
        self.wait_since_mono = self.submit_mono
        self.wait_since_wall = self.submit_wall
        self.placed_mono: Optional[float] = None
        self.first_placed = False
        self.slices: List[int] = []
        self.preemptions = 0
        # The demanding job's trace id while this job is being
        # preempted / awaiting resume — the cross-link that keeps one
        # capacity incident in one timeline.
        self.preempt_trace: str = ""
        self.park_started_wall: float = 0.0
        self.reason = ""
        self.quota_logged = False
        self.done_wall: float = 0.0

    @property
    def band(self) -> str:
        return str(self.spec.priority)


class PoolScheduler:
    def __init__(
        self,
        pool,
        trace_sink=None,
        park_timeout_s: float = 120.0,
    ):
        self.pool = pool
        self.traces = trace_sink
        self.park_timeout_s = park_timeout_s
        self._lock = threading.RLock()
        self._jobs: Dict[str, _Job] = {}
        self._seq = 0
        self._scheduling = False
        self._dirty = False
        self._park_timers: Dict[str, threading.Timer] = {}
        self._terminal_fifo: deque = deque()
        # Fired (outside the lock) with each evicted terminal job id;
        # the pool master drops its PoolJobContext here.
        self.on_job_evicted: Optional[Callable[[str], None]] = None
        self._wait_samples: Dict[str, deque] = {}
        self._counters = {
            "submitted": 0,
            "placements": 0,
            "backfills": 0,
            "completions": 0,
            "preemptions": {},  # reason -> n
            "quota_denied": {},  # tenant -> n
        }

    # -- trace plumbing -----------------------------------------------------

    def _span(
        self, trace_id: str, name: str, start: float,
        dur: float = 0.0, **tags,
    ) -> None:
        if self.traces is not None and trace_id:
            self.traces.add_span(
                trace_id, name, start, dur_s=dur, **tags
            )

    @staticmethod
    def _subject(job_id: str) -> str:
        return f"pooljob:{job_id}"

    # -- submission ---------------------------------------------------------

    def submit(
        self, spec: PoolJobSpec, runtime: JobRuntime
    ) -> Dict[str, str]:
        """Queue a job. Idempotent on job_id. Returns
        {"state": ..., "reason": ..., "trace_id": ...}."""
        if not spec.job_id:
            return {"state": "", "reason": "job_id required",
                    "trace_id": ""}
        if not 0 <= spec.priority <= MAX_PRIORITY:
            return {
                "state": "",
                "reason": f"priority must be 0..{MAX_PRIORITY}",
                "trace_id": "",
            }
        if spec.n_slices < 1 or spec.n_slices > self.pool.n_slices:
            return {
                "state": "",
                "reason": (
                    f"n_slices {spec.n_slices} outside pool capacity "
                    f"1..{self.pool.n_slices}"
                ),
                "trace_id": "",
            }
        with self._lock:
            existing = self._jobs.get(spec.job_id)
            if existing is not None:
                return {
                    "state": existing.state,
                    "reason": "already submitted",
                    "trace_id": existing.trace_id,
                }
            trace_id = f"pool-{spec.job_id}-{uuid.uuid4().hex[:8]}"
            job = _Job(spec, runtime, self._seq, trace_id)
            self._seq += 1
            self._jobs[spec.job_id] = job
            self._counters["submitted"] += 1
        self._span(
            trace_id, "pool.submit", job.submit_wall,
            subject=self._subject(spec.job_id), job_id=spec.job_id,
            tenant=spec.tenant, priority=spec.priority,
            n_slices=spec.n_slices,
        )
        obs.event(
            "pool.submit", job_id=spec.job_id, tenant=spec.tenant,
            priority=spec.priority, n_slices=spec.n_slices,
            trace_id=trace_id,
        )
        self.schedule_once()
        with self._lock:
            return {
                "state": job.state,
                "reason": job.reason,
                "trace_id": trace_id,
            }

    # -- lifecycle from runtimes --------------------------------------------

    def _note_terminal_locked(self, job_id: str) -> List[str]:
        """Ring-bound the terminal-record history; returns evicted
        job ids (callback fired by the caller outside the lock)."""
        self._terminal_fifo.append(job_id)
        evicted: List[str] = []
        while len(self._terminal_fifo) > MAX_TERMINAL_JOBS:
            old = self._terminal_fifo.popleft()
            job = self._jobs.get(old)
            if job is not None and job.state in PoolJobState.TERMINAL:
                self._jobs.pop(old, None)
                evicted.append(old)
        return evicted

    def _ledger(self, hook: str, *args) -> None:
        """Best-effort capacity-ledger notification (the pool owns
        the ledger reference; accounting must never fail or deadlock
        a scheduling decision — the ledger lock is leaf-level)."""
        ledger = getattr(self.pool, "ledger", None)
        if ledger is None:
            return
        try:
            getattr(ledger, hook)(*args)
        except Exception:  # noqa: BLE001
            logger.warning(
                "capacity ledger %s hook failed", hook, exc_info=True
            )

    def _tenant_retired_locked(self, job: _Job) -> bool:
        """True when ``job`` was its tenant's last live pool job —
        the signal to purge the tenant's time series (the dead-tenant
        half of the departed-host purge discipline)."""
        tenant = job.spec.tenant
        return not any(
            j.spec.tenant == tenant
            and j.spec.job_id != job.spec.job_id
            and j.state not in PoolJobState.TERMINAL
            for j in self._jobs.values()
        )

    def _fire_evictions(self, evicted: List[str]) -> None:
        cb = self.on_job_evicted
        for job_id in evicted:
            if cb is not None:
                try:
                    cb(job_id)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "job eviction callback failed for %s", job_id
                    )

    def complete(self, job_id: str, success: bool = True) -> None:
        """The job's runtime reports it finished; frees its slices
        and re-schedules (parked jobs resume here)."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.state in PoolJobState.TERMINAL:
                return
            job.state = (
                PoolJobState.DONE if success else PoolJobState.FAILED
            )
            job.done_wall = time.time()
            released = self.pool.release(job_id)
            job.slices = []
            self._counters["completions"] += 1
            evicted = self._note_terminal_locked(job_id)
            self._update_gauges_locked()
            tenant_retired = self._tenant_retired_locked(job)
        self._ledger("retire_job", job_id, tenant_retired)
        self._fire_evictions(evicted)
        self._span(
            job.trace_id, "pool.complete", job.done_wall,
            subject=self._subject(job_id), job_id=job_id,
            success=success, released=",".join(map(str, released)),
        )
        obs.event(
            "pool.complete", job_id=job_id, success=success,
            released=len(released),
        )
        self.schedule_once()

    def cancel(self, job_id: str) -> bool:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.state in PoolJobState.TERMINAL:
                return False
            was_running = job.state in PoolJobState.RUNNING
            job.state = PoolJobState.CANCELLED
            # Capacity: the interval between the cancel decision and
            # the slices returning to idle is drain, not production.
            if job.slices:
                self._ledger("mark_draining", job_id)
            self.pool.release(job_id)
            job.slices = []
            evicted = self._note_terminal_locked(job_id)
            self._update_gauges_locked()
            tenant_retired = self._tenant_retired_locked(job)
        self._ledger("retire_job", job_id, tenant_retired)
        self._fire_evictions(evicted)
        if was_running:
            try:
                job.runtime.stop()
            except Exception:  # noqa: BLE001
                logger.exception("stop() failed for %s", job_id)
        obs.event("pool.cancel", job_id=job_id)
        self.schedule_once()
        return True

    # -- scheduling pass ----------------------------------------------------

    def schedule_once(self) -> None:
        """One full scheduling pass. Reentrancy-safe: a pass already
        in flight absorbs nested calls (from synchronous runtime
        callbacks) as a re-run request instead of recursing."""
        with self._lock:
            if self._scheduling:
                self._dirty = True
                return
            self._scheduling = True
        try:
            for _ in range(64):  # progress-bounded, not time-bounded
                with self._lock:
                    self._dirty = False
                    actions = self._plan_locked()
                for fn in actions:
                    try:
                        fn()
                    except Exception:  # noqa: BLE001 — one broken
                        # runtime must not wedge the whole pool
                        logger.exception("pool runtime action failed")
                with self._lock:
                    if not actions and not self._dirty:
                        break
        finally:
            with self._lock:
                self._scheduling = False
                self._update_gauges_locked()

    def _waiting_locked(self) -> List[_Job]:
        return sorted(
            (
                j for j in self._jobs.values()
                if j.state in PoolJobState.WAITING
            ),
            key=lambda j: (-j.spec.priority, j.seq),
        )

    def _grant_size(self, job: _Job, free: int) -> int:
        """How many slices this placement attempt needs/takes. A
        fresh job demands its whole gang; a preempted elastic job may
        resume smaller (>= min_slices) and grow back later."""
        if free >= job.spec.n_slices:
            return job.spec.n_slices
        if (
            job.state == PoolJobState.PREEMPTED
            and job.spec.min_slices > 0
            and free >= job.spec.min_slices
        ):
            return free
        return 0

    def _plan_locked(self) -> List[Callable[[], None]]:
        """Compute the next batch of runtime actions under the lock;
        the caller executes them outside it."""
        actions: List[Callable[[], None]] = []
        waiting = self._waiting_locked()
        if not waiting:
            return actions
        free = self.pool.n_free()
        head_blocked: Optional[_Job] = None
        # Free slices earmarked for a blocked head whose gang will
        # become feasible through in-flight preemptions: backfill
        # must not re-occupy capacity the engine is actively freeing,
        # or the victim it just parked bounces straight back onto the
        # head's slices (placement churn, head never fits).
        reserved_free = 0
        for job in waiting:
            if head_blocked is not None:
                # Backfill: strictly lower-priority, whole gang in
                # the UNRESERVED holes, within quota. (Same band
                # would break FIFO; higher can't be behind the head.)
                if job.spec.priority >= head_blocked.spec.priority:
                    continue
                grant = self._grant_size(
                    job, max(free - reserved_free, 0)
                )
                # Whole gang in the holes, or an elastic resume
                # (_grant_size only returns a partial grant for
                # PREEMPTED jobs with a min_slices floor).
                if grant <= 0:
                    continue
            else:
                grant = self._grant_size(job, free)
            if grant <= 0:
                if head_blocked is None:
                    # Quota before head-blocking: an over-quota job
                    # is waiting on its OWN tenant's usage, not on
                    # pool capacity — letting it become the blocked
                    # head would starve same-band jobs of other
                    # tenants behind a gang that may never be
                    # quota-feasible.
                    if not self.pool.within_quota(
                        job.spec.tenant, job.spec.n_slices
                    ):
                        self._note_quota_denied_locked(job)
                        continue
                    head_blocked = job
                    feasible = self._maybe_preempt_for_locked(
                        job, actions
                    )
                    if feasible:
                        # Every currently-free slice is part of the
                        # head's incoming gang.
                        reserved_free = free
                continue
            if not self.pool.within_quota(job.spec.tenant, grant):
                self._note_quota_denied_locked(job)
                # Over-quota jobs are skipped over — they keep their
                # queue place but never block other tenants. They do
                # not become the blocked head either: nothing about
                # pool capacity blocks them, only their own quota.
                continue
            granted = self.pool.allocate(
                job.spec.job_id, job.spec.tenant, grant
            )
            if granted is None:
                if head_blocked is None:
                    head_blocked = job
                    self._maybe_preempt_for_locked(job, actions)
                continue
            actions.append(self._make_place_locked(job, granted,
                                                   head_blocked))
            free = self.pool.n_free()
        return actions

    def _note_quota_denied_locked(self, job: _Job) -> None:
        job.reason = (
            f"quota: tenant {job.spec.tenant!r} at cap "
            f"{self.pool.quota_of(job.spec.tenant)}"
        )
        if not job.quota_logged:
            job.quota_logged = True
            tenant = job.spec.tenant
            qd = self._counters["quota_denied"]
            qd[tenant] = qd.get(tenant, 0) + 1
            _QUOTA_DENIED.inc(tenant=tenant)
            obs.event(
                "pool.quota_denied", job_id=job.spec.job_id,
                tenant=tenant,
            )
            logger.info(
                "job %s queued over quota (%s)",
                job.spec.job_id, job.reason,
            )

    def _make_place_locked(
        self, job: _Job, granted: List[int],
        head_blocked: Optional[_Job],
    ) -> Callable[[], None]:
        """Transition to PLACED under the lock; return the runtime
        call for outside-lock execution."""
        now_wall = time.time()
        now_mono = time.monotonic()
        resume = job.state == PoolJobState.PREEMPTED
        backfilled = head_blocked is not None
        job.state = PoolJobState.PLACED
        job.slices = list(granted)
        job.placed_mono = now_mono
        job.reason = ""
        job.quota_logged = False
        wait_s = max(now_mono - job.wait_since_mono, 0.0)
        band = job.band
        self._wait_samples.setdefault(
            band, deque(maxlen=WAIT_SAMPLES_PER_BAND)
        ).append(wait_s)
        _WAIT_SECONDS.observe(wait_s, band=band)
        if not job.first_placed:
            job.first_placed = True
            _PLACEMENT_SECONDS.observe(wait_s)
        self._counters["placements"] += 1
        if backfilled:
            self._counters["backfills"] += 1
            _BACKFILLS.inc()
        # Queue-wait span covers this wait interval; then the
        # placement point span. On a resume, the span lands in the
        # demanding job's incident trace too.
        span_name = "pool.resume" if resume else "pool.place"
        self._span(
            job.trace_id, "pool.queue_wait", job.wait_since_wall,
            dur=wait_s, subject=self._subject(job.spec.job_id),
            job_id=job.spec.job_id, band=band,
        )
        self._span(
            job.trace_id, span_name, now_wall,
            subject=self._subject(job.spec.job_id),
            job_id=job.spec.job_id,
            slices=",".join(map(str, granted)),
            elastic=resume and len(granted) < job.spec.n_slices,
            backfill=backfilled,
        )
        if resume and job.preempt_trace:
            self._span(
                job.preempt_trace, "pool.resume", now_wall,
                subject=self._subject(job.spec.job_id),
                job_id=job.spec.job_id,
                slices=",".join(map(str, granted)),
                elastic=len(granted) < job.spec.n_slices,
            )
            job.preempt_trace = ""
        obs.event(
            "pool.place", job_id=job.spec.job_id,
            slices=",".join(map(str, granted)), resume=resume,
            backfill=backfilled, wait_s=round(wait_s, 3),
        )
        if resume:
            # Capacity: a resumed gang restores from checkpoint
            # before it produces; CapacityLedger.job_ready (workers
            # re-registered) flips it back to allocated.
            self._ledger("mark_restoring", job.spec.job_id)
        logger.info(
            "%s job %s on slices %s (waited %.2fs%s)",
            "resuming" if resume else "placing",
            job.spec.job_id, granted, wait_s,
            ", backfill" if backfilled else "",
        )
        runtime, slices = job.runtime, list(granted)
        return lambda: runtime.place(slices, resume)

    # -- preemption ---------------------------------------------------------

    def _maybe_preempt_for_locked(
        self, head: _Job, actions: List[Callable[[], None]]
    ) -> bool:
        """Evict the cheapest lower-priority victims so ``head``'s
        gang becomes feasible. Returns True when the gang WILL fit
        once in-flight/initiated parks confirm (the planner then
        reserves the free holes for it); False when even evicting
        every lower-priority job would not fit it — waiting on
        completions is then the only option, and backfill into the
        holes stays allowed (the head can preempt the backfilled job
        once its gang turns feasible)."""
        if not self.pool.within_quota(
            head.spec.tenant, head.spec.n_slices
        ):
            self._note_quota_denied_locked(head)
            return False
        pending = sum(
            len(j.slices)
            for j in self._jobs.values()
            if j.state == PoolJobState.PREEMPTING
        )
        shortfall = (
            head.spec.n_slices - self.pool.n_free() - pending
        )
        if shortfall <= 0:
            return True  # enough capacity already in flight
        victims = sorted(
            (
                j for j in self._jobs.values()
                if j.state == PoolJobState.PLACED
                and j.spec.priority < head.spec.priority
            ),
            key=lambda j: (
                j.spec.priority,
                -(j.placed_mono or 0.0),  # youngest first
            ),
        )
        chosen: List[_Job] = []
        gain = 0
        for v in victims:
            if gain >= shortfall:
                break
            chosen.append(v)
            gain += len(v.slices)
        if gain < shortfall:
            head.reason = (
                f"waiting: needs {head.spec.n_slices}, "
                f"{self.pool.n_free()} free, only {gain} "
                "preemptible"
            )
            return False
        head.reason = (
            f"preempting {[v.spec.job_id for v in chosen]}"
        )
        for v in chosen:
            self._start_park_locked(v, head, actions)
        return True

    def _start_park_locked(
        self, victim: _Job, head: _Job,
        actions: List[Callable[[], None]],
    ) -> None:
        victim.state = PoolJobState.PREEMPTING
        victim.park_started_wall = time.time()
        victim.preempt_trace = head.trace_id
        # Capacity: park -> checkpoint -> release is preemption
        # overhead, not production, from this decision onward.
        self._ledger("mark_preempting", victim.spec.job_id)
        obs.event(
            "pool.preempt", job_id=victim.spec.job_id,
            for_job=head.spec.job_id,
            victim_priority=victim.spec.priority,
            head_priority=head.spec.priority,
            trace_id=head.trace_id,
        )
        logger.warning(
            "preempting job %s (band %d) for job %s (band %d): "
            "graceful park -> checkpoint -> release",
            victim.spec.job_id, victim.spec.priority,
            head.spec.job_id, head.spec.priority,
        )
        job_id = victim.spec.job_id
        runtime = victim.runtime
        deadline = time.monotonic() + self.park_timeout_s

        def on_parked(info: Optional[dict] = None) -> None:
            self._finish_park(job_id, info or {})

        def park_action() -> None:
            try:
                runtime.park(on_parked)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "park() failed for %s; forcing release", job_id
                )
                # forced: park() raising means the workers never got
                # their park actions — they are still running and
                # need the hard stop the forced path orders.
                self._finish_park(
                    job_id,
                    {"staged": False, "error": "park failed"},
                    forced=True,
                )
                return
            # Watchdog: a runtime that never confirms parks the whole
            # queue — reclaim forcibly after the timeout.
            timer = threading.Timer(
                max(deadline - time.monotonic(), 0.0),
                lambda: self._finish_park(
                    job_id, {"staged": False, "error": "park timeout"},
                    forced=True,
                ),
            )
            timer.daemon = True
            self._watch_park(job_id, timer)
            timer.start()

        actions.append(park_action)

    def _watch_park(self, job_id: str, timer) -> None:
        """Track the park watchdog so a prompt confirmation cancels
        it (a synchronous on_parked already flipped the state)."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is not None and job.state == PoolJobState.PREEMPTING:
                job.reason = "parking"
                self._park_timers[job_id] = timer
            else:
                timer.cancel()

    def _finish_park(
        self, job_id: str, info: dict, forced: bool = False
    ) -> None:
        """The victim's runtime confirmed the graceful park (or the
        watchdog fired). Checkpoint staging is verified BEFORE the
        slices go back to the pool — the ordering the drill asserts."""
        with self._lock:
            # Drop the watchdog entry FIRST: a confirmation arriving
            # for a job that left PREEMPTING some other way (completed
            # or cancelled mid-park) must still clean up its timer
            # slot, or the dict grows one dead entry per such race.
            timer = self._park_timers.pop(job_id, None)
            if timer is not None:
                timer.cancel()
            job = self._jobs.get(job_id)
            if job is None or job.state != PoolJobState.PREEMPTING:
                return  # duplicate confirmation / already reclaimed
            staged = bool(info.get("staged"))
            # priority = clean graceful park; forced = the watchdog
            # reclaimed, park() itself failed, or the runtime reports
            # workers never parked (info["forced"]) — workers may
            # still be running; unstaged = workers parked cleanly but
            # the checkpoint never confirmed staging.
            forced = forced or bool(info.get("forced"))
            if forced:
                reason = "forced"
            elif staged:
                reason = "priority"
            else:
                reason = "unstaged"
            now_wall = time.time()
            # Park span: covers park start -> checkpoint staged.
            self._span(
                job.preempt_trace, "pool.park",
                job.park_started_wall,
                dur=max(now_wall - job.park_started_wall, 0.0),
                subject=self._subject(job_id), job_id=job_id,
                staged=staged,
                ckpt_path=str(info.get("path", "")),
                ckpt_step=info.get("step", -1),
            )
            self._span(
                job.trace_id, "pool.preempted", now_wall,
                subject=self._subject(job_id), job_id=job_id,
                staged=staged, reason=reason,
                for_trace=job.preempt_trace,
            )
            released = self.pool.release(job_id)
            job.slices = []
            job.state = PoolJobState.PREEMPTED
            job.preemptions += 1
            job.wait_since_mono = time.monotonic()
            job.wait_since_wall = now_wall
            job.reason = "preempted; awaiting capacity"
            self._span(
                job.preempt_trace, "pool.release", now_wall,
                subject=self._subject(job_id), job_id=job_id,
                slices=",".join(map(str, released)),
            )
            pre = self._counters["preemptions"]
            pre[reason] = pre.get(reason, 0) + 1
            _PREEMPTIONS.inc(reason=reason)
            self._update_gauges_locked()
            runtime = job.runtime
        obs.event(
            "pool.parked", job_id=job_id, staged=staged,
            forced=forced, released=len(released),
        )
        if reason != "priority":
            # Anything but a clean graceful park: order a hard stop
            # before the slices are reused. After a FORCED reclaim
            # the victim's workers may still be running — they must
            # not double-occupy the hardware or double-report into
            # the ledger next to their own resume incarnation; after
            # an UNSTAGED park the stop is a no-op (workers already
            # exited) but costs nothing.
            logger.error(
                "job %s released %s (%s) — its resume will replay "
                "from the shard ledger%s",
                job_id, reason,
                info.get("error", "no staging confirmation"),
                "; ordering runtime stop before slice reuse"
                if reason == "forced" else "",
            )
            try:
                runtime.stop()
            except Exception:  # noqa: BLE001 — the reclaim must
                # proceed even when the wedged runtime can't be told
                logger.exception("stop() failed for %s", job_id)
        self.schedule_once()

    # -- observability ------------------------------------------------------

    def _update_gauges_locked(self) -> None:
        by_band: Dict[str, int] = {}
        by_state: Dict[str, int] = {}
        for j in self._jobs.values():
            by_state[j.state] = by_state.get(j.state, 0) + 1
            if j.state in PoolJobState.WAITING:
                by_band[j.band] = by_band.get(j.band, 0) + 1
        for band in set(by_band) | set(self._wait_samples):
            _QUEUE_DEPTH.set(by_band.get(band, 0), band=band)
        for state in (
            PoolJobState.QUEUED, PoolJobState.PLACED,
            PoolJobState.PREEMPTING, PoolJobState.PREEMPTED,
            PoolJobState.DONE, PoolJobState.FAILED,
        ):
            _JOBS.set(by_state.get(state, 0), state=state)

    def job_info(self, job_id: str) -> Optional[dict]:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            return self._job_info_locked(job)

    def _job_info_locked(self, job: _Job) -> dict:
        return {
            "job_id": job.spec.job_id,
            "tenant": job.spec.tenant,
            "priority": job.spec.priority,
            "queue": job.spec.queue,
            "n_slices": job.spec.n_slices,
            "min_slices": job.spec.min_slices,
            "state": job.state,
            "slices": list(job.slices),
            "preemptions": job.preemptions,
            "trace_id": job.trace_id,
            "reason": job.reason,
            "submitted_ts": job.submit_wall,
            "waiting_s": (
                round(time.monotonic() - job.wait_since_mono, 3)
                if job.state in PoolJobState.WAITING
                else 0.0
            ),
        }

    def snapshot(self) -> dict:
        """The obs_report --pool feed: queue depth per band, tenant
        quota usage, slice utilization, preemption counters, and
        wait-time percentiles per band."""
        from dlrover_tpu.obs.timeseries import _percentile

        with self._lock:
            jobs = {
                jid: self._job_info_locked(j)
                for jid, j in self._jobs.items()
            }
            queue_depth: Dict[str, int] = {}
            queue_order = [
                j.spec.job_id for j in self._waiting_locked()
            ]
            for j in self._jobs.values():
                if j.state in PoolJobState.WAITING:
                    queue_depth[j.band] = (
                        queue_depth.get(j.band, 0) + 1
                    )
            waits = {
                band: sorted(samples)
                for band, samples in self._wait_samples.items()
                if samples
            }
            counters = {
                "submitted": self._counters["submitted"],
                "placements": self._counters["placements"],
                "backfills": self._counters["backfills"],
                "completions": self._counters["completions"],
                "preemptions": dict(self._counters["preemptions"]),
                "quota_denied": dict(self._counters["quota_denied"]),
            }
        pool_snap = self.pool.snapshot()
        return {
            "slices": pool_snap,
            "utilization": (
                1.0
                - len(pool_snap["free_slices"])
                / max(pool_snap["total_slices"], 1)
            ),
            "jobs": jobs,
            "queue_depth": queue_depth,
            "queue_order": queue_order,
            "counters": counters,
            "wait_seconds": {
                band: {
                    "count": len(s),
                    "p50": round(_percentile(s, 50.0), 4),
                    "p90": round(_percentile(s, 90.0), 4),
                    "p99": round(_percentile(s, 99.0), 4),
                }
                for band, s in waits.items()
            },
        }


def render_pool(snapshot: dict) -> str:
    """Human rendering of a PoolScheduler snapshot — the body of
    ``obs_report --pool``."""
    lines = []
    slices = snapshot.get("slices", {})
    total = slices.get("total_slices", 0)
    free = len(slices.get("free_slices", []))
    util = snapshot.get("utilization", 0.0)
    lines.append(
        f"pool: {total} slice(s), {free} free "
        f"(utilization {util * 100:.0f}%)"
    )
    depth = snapshot.get("queue_depth", {})
    if depth:
        by_band = "  ".join(
            f"band {b}: {n}"
            for b, n in sorted(
                depth.items(), key=lambda kv: -int(kv[0])
            )
        )
        order = snapshot.get("queue_order", [])
        lines.append(
            f"queue depth: {sum(depth.values())} ({by_band})"
            + (f"; order: {', '.join(order)}" if order else "")
        )
    else:
        lines.append("queue depth: 0")
    tenants = slices.get("tenants", {})
    if tenants:
        lines.append("tenants:")
        for tenant in sorted(tenants):
            t = tenants[tenant]
            quota = t.get("quota")
            lines.append(
                f"  {tenant}: {t.get('used', 0)}/"
                f"{quota if quota is not None else 'unlimited'} "
                "slice(s)"
            )
    jobs = snapshot.get("jobs", {})
    if jobs:
        lines.append("jobs:")
        for jid in sorted(
            jobs, key=lambda j: (-jobs[j]["priority"], j)
        ):
            j = jobs[jid]
            extra = []
            if j.get("slices"):
                extra.append(
                    "slices "
                    + ",".join(map(str, j["slices"]))
                )
            if j.get("preemptions"):
                extra.append(f"preempted x{j['preemptions']}")
            if j.get("reason"):
                extra.append(j["reason"])
            lines.append(
                f"  {jid}  tenant={j['tenant']}  "
                f"band={j['priority']}  {j['state']}"
                + ("  " + "; ".join(extra) if extra else "")
            )
    c = snapshot.get("counters", {})
    lines.append(
        f"counters: submitted {c.get('submitted', 0)}, placements "
        f"{c.get('placements', 0)}, backfills "
        f"{c.get('backfills', 0)}, completions "
        f"{c.get('completions', 0)}"
    )
    pre = c.get("preemptions", {})
    lines.append(
        "preemptions: "
        + (
            ", ".join(
                f"{r}={n}" for r, n in sorted(pre.items())
            )
            if pre
            else "none"
        )
    )
    qd = c.get("quota_denied", {})
    if qd:
        lines.append(
            "quota-denied: "
            + ", ".join(f"{t}={n}" for t, n in sorted(qd.items()))
        )
    waits = snapshot.get("wait_seconds", {})
    for band in sorted(waits, key=int, reverse=True):
        w = waits[band]
        lines.append(
            f"wait band {band}: p50 {w['p50']:.3f}s  "
            f"p90 {w['p90']:.3f}s  p99 {w['p99']:.3f}s  "
            f"(n={w['count']})"
        )
    return "\n".join(lines)
