"""Low-bit (4/8-bit state) Adam backed by the Pallas quantization
kernels.

Parity target: the reference's low-bit optimizers
(atorch/optimizers/low_bit/ + CUDA kernels
atorch/ops/csrc/quantization/quantization_optimizer.{cc,cu}, which
support 4- and 8-bit states): optimizer moments live in int8 (or
packed int4) with per-block float32 scales, cutting optimizer HBM
from 8 bytes/param (f32 m+v) to ~2 (8-bit) or ~1 (4-bit) bytes/param,
which is what makes large-model training fit on fewer chips.

Each update dequantizes the moments, applies the Adam rule in float32,
and requantizes — the quantize/dequantize run as Pallas kernels
(ops/quantization.py) on TPU. At 4 bits the first moment uses signed
levels (-7..7) and the second moment — stored as sqrt(v), which is
non-negative — uses unsigned levels (0..15) for double resolution.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import chex
import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.ops.quantization import (
    DEFAULT_BLOCK,
    dequantize_blockwise,
    dequantize_blockwise_4bit,
    quantize_blockwise,
    quantize_blockwise_4bit,
)


class _QTensor(NamedTuple):
    q: chex.Array  # int8 [rows, block] | packed uint8 [rows, block/2]
    scales: chex.Array  # f32 [rows, 1]


class Adam8bitState(NamedTuple):
    count: chex.Array
    mu: chex.ArrayTree  # tree of _QTensor
    nu: chex.ArrayTree  # tree of _QTensor


def _quant(x, block, bits=8, signed=True):
    if bits == 4:
        q, scales, _ = quantize_blockwise_4bit(x, block, signed)
    else:
        q, scales, _ = quantize_blockwise(x, block)
    return _QTensor(q=q, scales=scales)


def _dequant(qt: _QTensor, shape, bits=8, signed=True):
    if bits == 4:
        return dequantize_blockwise_4bit(qt.q, qt.scales, shape, signed)
    return dequantize_blockwise(qt.q, qt.scales, shape)


def adam_8bit(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    block_size: int = DEFAULT_BLOCK,
    min_quantize_size: int = 4096,
    update_clip: float = 2.0,
    bits: int = 8,
) -> optax.GradientTransformation:
    """AdamW with blockwise-quantized moments (int8, or packed int4
    with ``bits=4`` — see ``adam_4bit``).

    Leaves smaller than ``min_quantize_size`` keep float32 moments
    (quantization overhead/loss isn't worth it for biases/norms —
    same policy as the reference's low-bit optimizers which only
    quantize large tensors).

    ``update_clip`` bounds the preconditioned update per coordinate:
    m and sqrt(v) quantize against different block absmax values, so a
    coordinate's v can round to zero while its m survives, and
    m/(sqrt(v)+eps) would explode. Exact-Adam updates are ~O(1), so a
    clip at 2 never binds on healthy coordinates (the reference's
    low-bit suite relies on the same trust-region idea).
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")

    def _big(p) -> bool:
        return p.size >= min_quantize_size

    def init_fn(params):
        def init_moment(p, signed=True):
            if _big(p):
                return _quant(
                    jnp.zeros(p.shape, jnp.float32), block_size,
                    bits, signed,
                )
            return jnp.zeros(p.shape, jnp.float32)

        return Adam8bitState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree.map(init_moment, params),
            nu=jax.tree.map(
                lambda p: init_moment(p, signed=False), params
            ),
        )

    def update_fn(updates, state, params=None):
        count = state.count + 1
        t = count.astype(jnp.float32)
        bc1 = 1.0 - b1**t
        bc2 = 1.0 - b2**t

        is_q = jax.tree.map(
            _big, updates, is_leaf=lambda x: isinstance(x, jax.Array)
        )

        def leaf_update(g, mu, nu, quantized):
            g = g.astype(jnp.float32)
            if quantized:
                m = _dequant(mu, g.shape, bits)
                # v is stored as sqrt(v): linear quantization on
                # sqrt(v) keeps the quantization threshold
                # proportional to |g| for BOTH moments, so a
                # coordinate whose m survives quantization never sees
                # its v collapse to zero (which would explode
                # m/(sqrt(v)+eps)). sqrt(v) is non-negative, so at 4
                # bits it uses the unsigned 0..15 levels.
                v = jnp.square(
                    _dequant(nu, g.shape, bits, signed=False)
                )
            else:
                m, v = mu, nu
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            out = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if update_clip is not None:
                out = jnp.clip(out, -update_clip, update_clip)
            if quantized:
                m_s = _quant(m, block_size, bits)
                v_s = _quant(
                    jnp.sqrt(v), block_size, bits, signed=False
                )
            else:
                m_s, v_s = m, v
            return out, m_s, v_s

        flat_u, treedef = jax.tree.flatten(updates)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        flat_q = jax.tree.leaves(is_q)
        outs, new_mu, new_nu = [], [], []
        for g, mu, nu, quantized in zip(
            flat_u, flat_mu, flat_nu, flat_q
        ):
            o, m_s, v_s = leaf_update(g, mu, nu, quantized)
            outs.append(o)
            new_mu.append(m_s)
            new_nu.append(v_s)
        return (
            jax.tree.unflatten(treedef, outs),
            Adam8bitState(
                count=count,
                mu=jax.tree.unflatten(treedef, new_mu),
                nu=jax.tree.unflatten(treedef, new_nu),
            ),
        )

    core = optax.GradientTransformation(init_fn, update_fn)
    tx = [core]
    if weight_decay:
        tx.append(optax.add_decayed_weights(weight_decay))
    tx.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*tx)


def adam_4bit(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    **kw,
) -> optax.GradientTransformation:
    """AdamW with packed-int4 moments (~1 byte/param of optimizer
    state): signed 4-bit first moment, unsigned 4-bit sqrt(v). Same
    trust-region clip as the 8-bit variant. Ref: the 4-bit mode of
    atorch's quantization_optimizer kernels."""
    return adam_8bit(learning_rate, bits=4, **kw)


def optimizer_state_bytes(opt_state) -> Tuple[int, int]:
    """(actual_bytes, f32_equivalent_bytes) of all moment arrays —
    used by tests and the memory accounting in the strategy engine.
    uint8 leaves are the packed-int4 states (two logical values per
    byte), so their f32 equivalent is 2 * size * 4."""
    actual = 0
    f32_equiv = 0
    for leaf in jax.tree.leaves(opt_state):
        actual += leaf.size * leaf.dtype.itemsize
        logical = (
            leaf.size * 2 if leaf.dtype == jnp.uint8 else leaf.size
        )
        f32_equiv += logical * 4
    return actual, f32_equiv
