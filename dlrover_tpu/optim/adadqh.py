"""AdaDQH: Ant's adaptive quasi-Hessian optimizer, dense form.

AdaDQH is the earlier name of the rule published as AGD ("Auto-
switchable optimizer using stepwise gradient Difference as
preconditioning", NeurIPS'23); the tfplus sparse surface keeps the
old name (ref registrations: tfplus/kv_variable/ops/training_ops.cc
ApplyAdaDQH / KvVariableGroupSparseApplyAdaDQHV2 / ComputeAdaDQHHG).
The dense update is exactly :mod:`dlrover_tpu.optim.agd`'s core with
the switching threshold named ``eps``:

    m_t   = b1 m + (1-b1) g
    u_t   = m_t/(1-b1^t) - m_{t-1}/(1-b1^{t-1})
    v_t   = b2 v + (1-b2) u_t^2
    p    -= lr * m_t/(1-b1^t) / max(sqrt(v_t/(1-b2^t)), eps)

so :func:`adadqh` is a thin alias (kept so CTR/sparse configs can name
the same family on their dense towers). The distinctive extra surface
is :func:`adadqh_hypergradients` — per-element hyper-gradients of the
loss wrt lr and eps (the reference's ComputeAdaDQHHG op), used to
auto-tune the two knobs online from a dot product with the next
gradient.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import optax

from dlrover_tpu.optim.agd import agd


def adadqh(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-5,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Dense AdaDQH == AGD with delta renamed eps (see module doc)."""
    return agd(
        learning_rate=learning_rate,
        betas=(b1, b2),
        delta=eps,
        weight_decay=weight_decay,
    )


def adadqh_hypergradients(
    m: jnp.ndarray,
    v: jnp.ndarray,
    lr: float,
    eps: float,
    b1: float,
    b2: float,
    step: int,
    sam_delta: Optional[jnp.ndarray] = None,
    alpha: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-element hyper-gradients of the last AdaDQH update wrt
    (lr, eps) — the ComputeAdaDQHHG construction restated.

    ``m``/``v`` are the optimizer's moments AFTER the step-``step``
    update. The returned ``lr_hg`` is d(param)/d(lr) (the negated
    normalized momentum direction); ``eps_hg`` is d(param)/d(eps),
    nonzero only where the eps floor is the active branch of the
    max() switch. An outer tuner dots these with the next gradient to
    descend on the hyperparameters. ``sam_delta``/``alpha`` add the
    sharpness-aware term of the reference's SAM variant.

    Uses the PREVIOUS step's bias corrections (the update being
    differentiated happened before the moments advanced).
    """
    t_prev = max(step - 1, 1)
    bc1 = 1.0 - b1**t_prev
    bc2 = 1.0 - b2**t_prev
    adjust = jnp.sqrt(bc2) / bc1
    eps_adj = eps * jnp.sqrt(bc2)
    root_v = jnp.sqrt(v)
    denom = jnp.maximum(root_v, eps_adj)
    floored = (eps_adj >= root_v).astype(m.dtype)
    lr_hg = -adjust * m / denom
    # d(update)/d(eps) in the floored branch: the floor is
    # eps*sqrt(bc2), so the chain rule carries a sqrt(bc2) factor
    # (verified by finite difference — without it eps steps inflate
    # by 1/sqrt(bc2), ~22x at t=3 with b2=0.999).
    eps_hg = (
        lr * adjust * m * jnp.sqrt(bc2) / jnp.square(denom) * floored
    )
    if sam_delta is not None:
        lr_hg = lr_hg - (1.0 - alpha) * sam_delta
    return lr_hg, eps_hg
