"""Weighted Sharpness-Aware Minimization (KDD'23), JAX-native.

Parity with the reference's torch WeightedSAM
(atorch/optimizers/wsam.py:11-140): two forward/backward passes per
step — climb to w+e(w) along the normalized gradient (first_step :50),
take the base-optimizer step using the sharpness-weighted gradient
(second_step :74) — with ``decouple=True`` applying the sharpness term
as a separate additive correction.

The torch version needs DDP no_sync + allreduce choreography; under
pjit both gradient evaluations are just calls of the same compiled
grad function, and any data-parallel averaging is already inside it.
The whole two-pass step is one jittable function.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import optax


def _global_norm(tree) -> jax.Array:
    return jnp.sqrt(
        sum(
            jnp.sum(jnp.square(x.astype(jnp.float32)))
            for x in jax.tree.leaves(tree)
        )
    )


class WSAMState(NamedTuple):
    base_state: chex.ArrayTree


class WeightedSAM:
    """Wraps a base optax optimizer with the WSAM two-pass step.

    Parameters mirror the reference: rho (perturbation radius), gamma
    (sharpness weight; alpha = gamma/(1-gamma)), adaptive (scale the
    perturbation by |p|, ASAM-style), decouple (sharpness as decoupled
    correction), max_norm (grad clipping before each use).

    Use ``make_step(grad_fn)`` where grad_fn(params, *batch) ->
    (loss, grads); the returned function is jit-compatible:

        step = jax.jit(wsam.make_step(jax.value_and_grad(loss_fn)))
        params, state, loss = step(params, state, batch...)
    """

    def __init__(
        self,
        base_optimizer: optax.GradientTransformation,
        rho: float = 0.05,
        gamma: float = 0.9,
        sam_eps: float = 1e-12,
        adaptive: bool = False,
        decouple: bool = True,
        max_norm: Optional[float] = None,
        learning_rate: Optional[float] = None,
    ):
        self.base = base_optimizer
        self.rho = rho
        self.alpha = gamma / (1.0 - gamma)
        self.sam_eps = sam_eps
        self.adaptive = adaptive
        self.decouple = decouple
        self.max_norm = max_norm
        # The decoupled correction needs the base lr (ref second_step
        # uses group["lr"]); optax hides it inside the chain, so it is
        # passed explicitly when decouple=True.
        self.learning_rate = learning_rate
        if decouple and learning_rate is None:
            raise ValueError(
                "decouple=True needs learning_rate= (the reference "
                "reads it from the param group)"
            )

    def init(self, params) -> WSAMState:
        return WSAMState(base_state=self.base.init(params))

    def _clip(self, grads):
        if self.max_norm is None:
            return grads
        norm = _global_norm(grads)
        scale = jnp.minimum(1.0, self.max_norm / (norm + 1e-12))
        return jax.tree.map(lambda g: g * scale, grads)

    def make_step(
        self, grad_fn: Callable
    ) -> Callable:
        def step(params, state: WSAMState, *batch):
            loss, g1 = grad_fn(params, *batch)
            g1 = self._clip(g1)
            # -- first step: climb to the local maximum w + e(w).
            # Adaptive (ASAM) normalizes by ||abs(p)*g|| so the
            # perturbation radius stays rho in the rescaled geometry
            # (ref _grad_norm, wsam.py:123-140).
            if self.adaptive:
                gnorm = _global_norm(
                    jax.tree.map(
                        lambda p, g: jnp.abs(p) * g, params, g1
                    )
                )
                scale = self.rho / (gnorm + self.sam_eps)
                e_w = jax.tree.map(
                    lambda p, g: jnp.square(p) * g * scale, params, g1
                )
            else:
                gnorm = _global_norm(g1)
                scale = self.rho / (gnorm + self.sam_eps)
                e_w = jax.tree.map(lambda g: g * scale, g1)
            perturbed = jax.tree.map(jnp.add, params, e_w)
            # -- second gradient at the perturbed point
            _, g2 = grad_fn(perturbed, *batch)
            g2 = self._clip(g2)

            if self.decouple:
                sharpness = jax.tree.map(jnp.subtract, g2, g1)
                updates, base_state = self.base.update(
                    g1, state.base_state, params
                )
                new_params = optax.apply_updates(params, updates)
                new_params = jax.tree.map(
                    lambda p, s: p
                    - self.learning_rate * self.alpha * s,
                    new_params,
                    sharpness,
                )
            else:
                mixed = jax.tree.map(
                    lambda a, b: self.alpha * b + (1.0 - self.alpha) * a,
                    g1,
                    g2,
                )
                updates, base_state = self.base.update(
                    mixed, state.base_state, params
                )
                new_params = optax.apply_updates(params, updates)
            return new_params, WSAMState(base_state=base_state), loss

        return step
