"""AGD optimizer (NeurIPS'23) as an optax transformation.

"AGD: an Auto-switchable Optimizer using Stepwise Gradient Difference
as Preconditioning Matrix" — behavioral parity with the reference's
torch implementation (atorch/optimizers/agd.py:19-157, update rule
:120-156), re-stated functionally:

    m_t   = b1 m_{t-1} + (1-b1) g_t
    u_t   = m_t/(1-b1^t) - m_{t-1}/(1-b1^{t-1})      (u_1 = m_1/(1-b1))
    v_t   = b2 v_{t-1} + (1-b2) u_t^2
    denom = max(sqrt(v_t  or amsgrad-max), delta*sqrt(1-b2^t))
    p_t   = p_{t-1}(1 - lr*wd) - lr*sqrt(1-b2^t)/(1-b1^t) * m_t/denom

The reference claims up to 1.5x faster convergence than AdamW on
nanoGPT (atorch/docs/README-AGD.md:29, BASELINE.md) — the test suite
checks AGD beats AdamW on a quadratic benchmark.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import optax


class ScaleByAGDState(NamedTuple):
    count: chex.Array
    exp_avg: chex.ArrayTree
    exp_avg_sq: chex.ArrayTree
    max_exp_avg_sq: Optional[chex.ArrayTree]


def scale_by_agd(
    b1: float = 0.9,
    b2: float = 0.999,
    delta: float = 1e-5,
    amsgrad: bool = False,
    clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """The preconditioning core: g -> sqrt(bc2)/bc1 * m/denom.

    (Learning rate and weight decay are composed on top in :func:`agd`.)
    """

    def init_fn(params):
        zeros = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
        )
        return ScaleByAGDState(
            count=jnp.zeros([], jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=jax.tree.map(jnp.zeros_like, zeros),
            max_exp_avg_sq=(
                jax.tree.map(jnp.zeros_like, zeros) if amsgrad else None
            ),
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        t = count.astype(jnp.float32)
        bc1_old = 1.0 - b1 ** (t - 1.0)
        bc1 = 1.0 - b1**t
        bc2 = 1.0 - b2**t

        m_old = state.exp_avg
        m_new = jax.tree.map(
            lambda m, g: b1 * m + (1.0 - b1) * g.astype(jnp.float32),
            m_old,
            updates,
        )
        # Stepwise gradient difference preconditioner. At t=1 the
        # previous bias correction divides by zero; the reference
        # special-cases it to m_1/bc1 — jnp.where keeps it jittable.
        safe_bc1_old = jnp.where(count == 1, 1.0, bc1_old)
        u = jax.tree.map(
            lambda mn, mo: jnp.where(
                count == 1,
                mn / bc1,
                mn / bc1 - mo / safe_bc1_old,
            ),
            m_new,
            m_old,
        )
        v_new = jax.tree.map(
            lambda v, uu: b2 * v + (1.0 - b2) * uu * uu,
            state.exp_avg_sq,
            u,
        )
        if amsgrad:
            max_v = jax.tree.map(
                jnp.maximum, state.max_exp_avg_sq, v_new
            )
            denom_src = max_v
        else:
            max_v = None
            denom_src = v_new

        delta_adjust = delta * jnp.sqrt(bc2)

        def precond(mn, v):
            denom = jnp.maximum(jnp.sqrt(v), delta_adjust)
            out = mn / denom
            if clip is not None:
                out = jnp.clip(out, -clip, clip)
            return out * (jnp.sqrt(bc2) / bc1)

        out = jax.tree.map(precond, m_new, denom_src)
        return out, ScaleByAGDState(
            count=count,
            exp_avg=m_new,
            exp_avg_sq=v_new,
            max_exp_avg_sq=max_v,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def agd(
    learning_rate: optax.ScalarOrSchedule = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    delta: float = 1e-5,
    weight_decay: float = 0.0,
    amsgrad: bool = False,
    clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """Full AGD with decoupled weight decay (the reference default,
    weight_decouple=True fixed_decay=False: p *= 1 - lr*wd)."""
    tx = [
        scale_by_agd(
            b1=betas[0], b2=betas[1], delta=delta,
            amsgrad=amsgrad, clip=clip,
        )
    ]
    if weight_decay:
        tx.append(optax.add_decayed_weights(weight_decay))
    tx.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*tx)
