"""Optimizers (optax-native).

TPU-native re-implementations of the reference's optimizer suite
(atorch/optimizers/: AGD agd.py:19, WSAM wsam.py:11, low-bit
optimizers low_bit/ backed by the CUDA quantization ops). Here they
are pure optax transformations / jittable step wrappers — no parameter
mutation, no process groups; gradient averaging is whatever psum the
surrounding pjit inserts.
"""

from dlrover_tpu.optim.adadqh import (  # noqa: F401
    adadqh,
    adadqh_hypergradients,
)
from dlrover_tpu.optim.agd import agd, scale_by_agd  # noqa: F401
from dlrover_tpu.optim.low_bit import adam_4bit, adam_8bit  # noqa: F401
from dlrover_tpu.optim.wsam import WeightedSAM  # noqa: F401
