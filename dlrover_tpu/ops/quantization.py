"""Blockwise quantization kernels (Pallas).

TPU-native replacement for the reference's CUDA quantization suite
(atorch/ops/csrc/quantization/{quantize,dequantize,swizzled_quantize,
quant_reduce}.cu and the fused quantized-state optimizer kernel,
pt_binding.cpp:152-176). Symmetric per-block quantization: each block
of ``block_size`` contiguous values shares one float32 scale. Two bit
widths, matching the reference kernels' 4/8-bit support:

* int8 (scale = absmax/127), 1 byte/value;
* packed int4 (two nibbles per uint8 byte), 0.5 bytes/value — signed
  levels -7..7 for sign-changing state, unsigned 0..15 for
  non-negative state like sqrt(v).

Backs the low-bit optimizer states of optim/low_bit.py. The kernels
run compiled on TPU and interpreted on CPU (tests). Shapes are
flattened to [num_blocks, block_size]; block_size should be a
multiple of 128 (lane width). jnp reference paths are exported as the
ground truth in tests.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 1024
# Rows of blocks processed per kernel grid step (sublane packing).
_ROWS = 8


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Shared host-side scaffolding (flatten -> block rows -> pallas grid)
# ---------------------------------------------------------------------------


def _to_block_rows(x, block_size):
    """x (any shape) -> (x2 [rows_padded, block], true rows, shape)."""
    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.size // block_size
    x2 = flat.reshape(rows, block_size)
    row_pad = (-rows) % _ROWS
    if row_pad:
        x2 = jnp.pad(x2, ((0, row_pad), (0, 0)))
    return x2, rows, shape


def _row_spec(width):
    return pl.BlockSpec(
        (_ROWS, width), lambda i: (i, 0), memory_space=pltpu.VMEM
    )


def _quant_call(kernel, x2, out_width, out_dtype):
    """Run a quantize kernel over block rows -> (q, scales)."""
    grid = x2.shape[0] // _ROWS
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[_row_spec(x2.shape[1])],
        out_specs=[_row_spec(out_width), _row_spec(1)],
        out_shape=[
            jax.ShapeDtypeStruct((x2.shape[0], out_width), out_dtype),
            jax.ShapeDtypeStruct((x2.shape[0], 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2)


def _dequant_call(kernel, q, scales, block_size, dtype):
    """Run a dequantize kernel -> values [rows_padded, block]."""
    rows = q.shape[0]
    row_pad = (-rows) % _ROWS
    if row_pad:
        q = jnp.pad(q, ((0, row_pad), (0, 0)))
        scales = jnp.pad(scales, ((0, row_pad), (0, 0)))
    grid = q.shape[0] // _ROWS
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[_row_spec(q.shape[1]), _row_spec(1)],
        out_specs=_row_spec(block_size),
        out_shape=jax.ShapeDtypeStruct((q.shape[0], block_size), dtype),
        interpret=_use_interpret(),
    )(q, scales)


def _unflatten(out, rows, shape):
    n = 1
    for s in shape:
        n *= s
    return out[:rows].reshape(-1)[:n].reshape(shape)


# ---------------------------------------------------------------------------
# int8 kernels
# ---------------------------------------------------------------------------


def _quantize_kernel(x_ref, q_ref, scale_ref):
    x = x_ref[:].astype(jnp.float32)  # (_ROWS, block)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax / 127.0
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(x / safe), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    scale_ref[:] = scale


def _dequantize_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = (
        q_ref[:].astype(jnp.float32) * scale_ref[:]
    ).astype(out_ref.dtype)


def quantize_blockwise(
    x: jax.Array, block_size: int = DEFAULT_BLOCK
) -> Tuple[jax.Array, jax.Array, Tuple[int, ...]]:
    """x (any shape) -> (int8 values [n_blocks, block], f32 scales
    [n_blocks, 1], original shape). Tail is zero-padded (zero maps to
    zero exactly, so padding never perturbs scales of real data beyond
    the shared block — callers with hard accuracy needs should size
    params to block multiples)."""
    x2, rows, shape = _to_block_rows(x, block_size)
    q, scales = _quant_call(_quantize_kernel, x2, block_size, jnp.int8)
    return q[:rows], scales[:rows], shape


def dequantize_blockwise(
    q: jax.Array,
    scales: jax.Array,
    shape: Tuple[int, ...],
    dtype=jnp.float32,
) -> jax.Array:
    rows, block_size = q.shape
    out = _dequant_call(_dequantize_kernel, q, scales, block_size, dtype)
    return _unflatten(out, rows, shape)


# ---------------------------------------------------------------------------
# 4-bit (packed) kernels — two nibbles per uint8 byte
# ---------------------------------------------------------------------------
#
# Packing layout pairs element i with element i + block/2 (first half
# of the block in the low nibble, second half in the high nibble) so
# the kernel slices are contiguous lane runs, not stride-2 gathers.


def _quantize4_kernel(x_ref, q_ref, scale_ref, *, signed: bool):
    x = x_ref[:].astype(jnp.float32)  # (_ROWS, block)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    levels = 7.0 if signed else 15.0
    scale = absmax / levels
    safe = jnp.maximum(scale, 1e-30)
    if signed:
        q = jnp.clip(jnp.round(x / safe), -7, 7) + 8.0  # 1..15
    else:
        q = jnp.clip(jnp.round(x / safe), 0, 15)
    q = q.astype(jnp.int32)
    half = q.shape[1] // 2
    packed = q[:, :half] | (q[:, half:] << 4)
    q_ref[:] = packed.astype(jnp.uint8)
    scale_ref[:] = scale


def _dequantize4_kernel(q_ref, scale_ref, out_ref, *, signed: bool):
    p = q_ref[:].astype(jnp.int32)
    lo = p & 15
    hi = (p >> 4) & 15
    if signed:
        lo = lo - 8
        hi = hi - 8
    vals = jnp.concatenate([lo, hi], axis=1).astype(jnp.float32)
    out_ref[:] = (vals * scale_ref[:]).astype(out_ref.dtype)


def quantize_blockwise_4bit(
    x: jax.Array,
    block_size: int = DEFAULT_BLOCK,
    signed: bool = True,
) -> Tuple[jax.Array, jax.Array, Tuple[int, ...]]:
    """x (any shape) -> (uint8 packed [n_blocks, block/2], f32 scales
    [n_blocks, 1], original shape). 0.5 bytes/value + scale. signed:
    levels -7..7 (scale absmax/7); unsigned: 0..15 (absmax/15 — twice
    the resolution for non-negative state)."""
    x2, rows, shape = _to_block_rows(x, block_size)
    q, scales = _quant_call(
        functools.partial(_quantize4_kernel, signed=signed),
        x2, block_size // 2, jnp.uint8,
    )
    return q[:rows], scales[:rows], shape


def dequantize_blockwise_4bit(
    q: jax.Array,
    scales: jax.Array,
    shape: Tuple[int, ...],
    signed: bool = True,
    dtype=jnp.float32,
) -> jax.Array:
    rows, half = q.shape
    out = _dequant_call(
        functools.partial(_dequantize4_kernel, signed=signed),
        q, scales, half * 2, dtype,
    )
    return _unflatten(out, rows, shape)


# ---------------------------------------------------------------------------
# jnp references (ground truth for tests; also handle tiny arrays)
# ---------------------------------------------------------------------------


def quantize_blockwise_ref(x, block_size: int = DEFAULT_BLOCK):
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.size) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2 = flat.reshape(-1, block_size)
    scale = jnp.max(jnp.abs(x2), axis=1, keepdims=True) / 127.0
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(x2 / safe), -127, 127).astype(jnp.int8)
    return q, scale, shape


def dequantize_blockwise_ref(q, scales, shape, dtype=jnp.float32):
    out = q.astype(jnp.float32) * scales
    n = 1
    for s in shape:
        n *= s
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantize_blockwise_4bit_ref(
    x, block_size: int = DEFAULT_BLOCK, signed: bool = True
):
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.size) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2 = flat.reshape(-1, block_size)
    levels = 7.0 if signed else 15.0
    scale = jnp.max(jnp.abs(x2), axis=1, keepdims=True) / levels
    safe = jnp.maximum(scale, 1e-30)
    if signed:
        q = (jnp.clip(jnp.round(x2 / safe), -7, 7) + 8).astype(jnp.int32)
    else:
        q = jnp.clip(jnp.round(x2 / safe), 0, 15).astype(jnp.int32)
    half = block_size // 2
    packed = (q[:, :half] | (q[:, half:] << 4)).astype(jnp.uint8)
    return packed, scale, shape


def dequantize_blockwise_4bit_ref(
    q, scales, shape, signed: bool = True, dtype=jnp.float32
):
    p = q.astype(jnp.int32)
    lo, hi = p & 15, (p >> 4) & 15
    if signed:
        lo, hi = lo - 8, hi - 8
    vals = jnp.concatenate([lo, hi], axis=1).astype(jnp.float32)
    out = vals * scales
    n = 1
    for s in shape:
        n *= s
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)
