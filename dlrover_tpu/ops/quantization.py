"""Blockwise quantization kernels (Pallas).

TPU-native replacement for the reference's CUDA quantization suite
(atorch/ops/csrc/quantization/{quantize,dequantize,swizzled_quantize,
quant_reduce}.cu and the fused quantized-state optimizer kernel,
pt_binding.cpp:152-176). Symmetric per-block int8 quantization: each
block of ``block_size`` contiguous values shares one float32 scale
(absmax / 127). Backs the low-bit optimizer states of optim/low_bit.py.

The kernels run compiled on TPU and interpreted on CPU (tests). Shapes
are flattened to [num_blocks, block_size]; block_size should be a
multiple of 128 (lane width). A jnp reference path is exported for
odd sizes and as the ground truth in tests.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 1024
# Rows of blocks processed per kernel grid step (sublane packing).
_ROWS = 8


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _quantize_kernel(x_ref, q_ref, scale_ref):
    x = x_ref[:].astype(jnp.float32)  # (_ROWS, block)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax / 127.0
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(x / safe), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    scale_ref[:] = scale


def _dequantize_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = (
        q_ref[:].astype(jnp.float32) * scale_ref[:]
    ).astype(out_ref.dtype)


def quantize_blockwise(
    x: jax.Array, block_size: int = DEFAULT_BLOCK
) -> Tuple[jax.Array, jax.Array, Tuple[int, ...]]:
    """x (any shape) -> (int8 values [n_blocks, block], f32 scales
    [n_blocks, 1], original shape). Tail is zero-padded (zero maps to
    zero exactly, so padding never perturbs scales of real data beyond
    the shared block — callers with hard accuracy needs should size
    params to block multiples)."""
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.size
    pad = (-n) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.size // block_size
    x2 = flat.reshape(rows, block_size)

    row_pad = (-rows) % _ROWS
    if row_pad:
        x2 = jnp.pad(x2, ((0, row_pad), (0, 0)))
    grid = x2.shape[0] // _ROWS

    q, scales = pl.pallas_call(
        _quantize_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                (_ROWS, block_size), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=[
            pl.BlockSpec(
                (_ROWS, block_size), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_ROWS, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2.shape, jnp.int8),
            jax.ShapeDtypeStruct((x2.shape[0], 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2)
    return q[:rows], scales[:rows], shape


def dequantize_blockwise(
    q: jax.Array,
    scales: jax.Array,
    shape: Tuple[int, ...],
    dtype=jnp.float32,
) -> jax.Array:
    rows, block_size = q.shape
    row_pad = (-rows) % _ROWS
    if row_pad:
        q = jnp.pad(q, ((0, row_pad), (0, 0)))
        scales = jnp.pad(scales, ((0, row_pad), (0, 0)))
    grid = q.shape[0] // _ROWS
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                (_ROWS, block_size), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_ROWS, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (_ROWS, block_size), lambda i: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, dtype),
        interpret=_use_interpret(),
    )(q, scales)
    n = 1
    for s in shape:
        n *= s
    return out[:rows].reshape(-1)[:n].reshape(shape)


# ---------------------------------------------------------------------------
# jnp reference (ground truth for tests; also handles tiny arrays)
# ---------------------------------------------------------------------------


def quantize_blockwise_ref(x, block_size: int = DEFAULT_BLOCK):
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.size) % block_size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2 = flat.reshape(-1, block_size)
    scale = jnp.max(jnp.abs(x2), axis=1, keepdims=True) / 127.0
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(x2 / safe), -127, 127).astype(jnp.int8)
    return q, scale, shape


def dequantize_blockwise_ref(q, scales, shape, dtype=jnp.float32):
    out = q.astype(jnp.float32) * scales
    n = 1
    for s in shape:
        n *= s
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)
