"""Fused memory-efficient softmax cross-entropy over a tied embedding.

Parity with atorch's fused cross-entropy
(atorch/modules/transformer/cross_entropy.py:338LoC, a CUDA kernel
that avoids materializing log-softmax over the vocab): here the fusion
is chunking + custom_vjp. The naive path materializes TWO [B*T, V]
float32 tensors (logits and log-softmax) — 6.6 GB at batch 16, seq
1024, vocab 50k — and routes the backward matmuls through float32
cotangents (quarter-rate on the MXU). This implementation:

* never holds more than one [chunk, V] logits block (forward and
  backward recompute per chunk inside ``lax.map``);
* stores only the per-token logsumexp (f32 [N]) between fwd and bwd;
* emits bf16 cotangents into the unembedding matmuls so the backward
  runs at full MXU rate.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_cross_entropy(
    x, wte, targets, num_chunks: int = 8, save_logits: bool = False
):
    """Mean token cross-entropy of ``x @ wte^T`` against targets.

    x: [N, E] (activations, bf16 ok); wte: [V, E] tied embedding;
    targets: [N] int. N must be divisible by num_chunks (pad or pick a
    divisor; model code uses B*T which is a power of two).

    ``save_logits=True`` stashes the forward logits in x.dtype (bf16:
    2 bytes/entry, 1.6 GB at batch 16 x 1024 x 50k vocab) so the
    backward skips the [N,V] recompute matmul — ~V*E MACs/token of
    work MFU accounting never credits. Numerics caveat: with bf16
    activations the saved logits are rounded to bf16 before the
    backward ``exp``, so per-element softmax probabilities (and hence
    dlogits) carry a few-percent relative error versus the f32
    recompute path — zero-mean rounding noise on top of the bf16
    cotangent cast both paths share. Use it when HBM has room and
    bf16-grade gradients are acceptable (the GPT-2 bench regime);
    leave it off at Llama-7B scale where the recompute is the right
    trade, or when gradient bit-accuracy matters.
    """
    loss, _ = _fwd(x, wte, targets, num_chunks, save_logits)
    return loss


def _fwd(x, wte, targets, num_chunks, save_logits):
    n = x.shape[0]
    xc = x.reshape(num_chunks, n // num_chunks, -1)
    tc = targets.reshape(num_chunks, -1)

    def chunk(args):
        x_c, t_c = args
        logits = jnp.einsum(
            "ce,ve->cv", x_c, wte, preferred_element_type=jnp.float32
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, t_c[:, None], axis=-1)[:, 0]
        saved = logits.astype(x.dtype) if save_logits else jnp.zeros(
            (0,), x.dtype
        )
        return lse, gold, saved

    lse, gold, saved = jax.lax.map(chunk, (xc, tc))
    loss = jnp.mean(lse - gold)
    return loss, (x, wte, targets, lse.reshape(-1), saved)


def _bwd(num_chunks, save_logits, res, g):
    x, wte, targets, lse, saved = res
    n = x.shape[0]
    c = n // num_chunks
    xc = x.reshape(num_chunks, c, -1)
    tc = targets.reshape(num_chunks, -1)
    lc = lse.reshape(num_chunks, -1)

    def chunk_grads(carry, args):
        x_c, t_c, lse_c, saved_c = args
        if save_logits:
            logits = saved_c.astype(jnp.float32)
        else:
            logits = jnp.einsum(
                "ce,ve->cv", x_c, wte,
                preferred_element_type=jnp.float32,
            )
        p = jnp.exp(logits - lse_c[:, None])
        dlogits = p - jax.nn.one_hot(t_c, wte.shape[0], dtype=p.dtype)
        dlogits = (dlogits * (g / n)).astype(x.dtype)  # bf16 cotangent
        dx_c = jnp.einsum("cv,ve->ce", dlogits, wte)
        dwte = carry + jnp.einsum(
            "cv,ce->ve", dlogits, x_c, preferred_element_type=jnp.float32
        )
        return dwte, dx_c

    dwte0 = jnp.zeros(wte.shape, jnp.float32)
    dwte, dxc = jax.lax.scan(chunk_grads, dwte0, (xc, tc, lc, saved))
    dx = dxc.reshape(x.shape)
    return dx, dwte.astype(wte.dtype), None


fused_cross_entropy.defvjp(
    lambda x, wte, t, nc, sl: _fwd(x, wte, t, nc, sl), _bwd
)
