"""Fused memory-efficient softmax cross-entropy over a tied embedding.

Parity with atorch's fused cross-entropy
(atorch/modules/transformer/cross_entropy.py:338LoC, a CUDA kernel
that avoids materializing log-softmax over the vocab): here the fusion
is chunking + custom_vjp. The naive path materializes TWO [B*T, V]
float32 tensors (logits and log-softmax) — 6.6 GB at batch 16, seq
1024, vocab 50k — and routes the backward matmuls through float32
cotangents (quarter-rate on the MXU). This implementation:

* never holds more than one [chunk, V] logits block (forward and
  backward recompute per chunk inside ``lax.map``);
* stores only the per-token logsumexp (f32 [N]) between fwd and bwd;
* emits bf16 cotangents into the unembedding matmuls so the backward
  runs at full MXU rate.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _chunk_lse_and_gold(x_c, wte, targets_c):
    """One chunk: (logsumexp [c], gold-logit [c]) in f32."""
    logits = jnp.einsum(
        "ce,ve->cv", x_c, wte, preferred_element_type=jnp.float32
    )
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, targets_c[:, None], axis=-1
    )[:, 0]
    return lse, gold


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_cross_entropy(x, wte, targets, num_chunks: int = 8):
    """Mean token cross-entropy of ``x @ wte^T`` against targets.

    x: [N, E] (activations, bf16 ok); wte: [V, E] tied embedding;
    targets: [N] int. N must be divisible by num_chunks (pad or pick a
    divisor; model code uses B*T which is a power of two).
    """
    loss, _ = _fwd(x, wte, targets, num_chunks)
    return loss


def _fwd(x, wte, targets, num_chunks):
    n = x.shape[0]
    xc = x.reshape(num_chunks, n // num_chunks, -1)
    tc = targets.reshape(num_chunks, -1)
    lse, gold = jax.lax.map(
        lambda args: _chunk_lse_and_gold(args[0], wte, args[1]),
        (xc, tc),
    )
    loss = jnp.mean(lse - gold)
    return loss, (x, wte, targets, lse.reshape(-1))


def _bwd(num_chunks, res, g):
    x, wte, targets, lse = res
    n = x.shape[0]
    c = n // num_chunks
    xc = x.reshape(num_chunks, c, -1)
    tc = targets.reshape(num_chunks, -1)
    lc = lse.reshape(num_chunks, -1)

    def chunk_grads(carry, args):
        x_c, t_c, lse_c = args
        logits = jnp.einsum(
            "ce,ve->cv", x_c, wte, preferred_element_type=jnp.float32
        )
        p = jnp.exp(logits - lse_c[:, None])
        dlogits = p - jax.nn.one_hot(t_c, wte.shape[0], dtype=p.dtype)
        dlogits = (dlogits * (g / n)).astype(x.dtype)  # bf16 cotangent
        dx_c = jnp.einsum("cv,ve->ce", dlogits, wte)
        dwte = carry + jnp.einsum(
            "cv,ce->ve", dlogits, x_c, preferred_element_type=jnp.float32
        )
        return dwte, dx_c

    dwte0 = jnp.zeros(wte.shape, jnp.float32)
    dwte, dxc = jax.lax.scan(chunk_grads, dwte0, (xc, tc, lc))
    dx = dxc.reshape(x.shape)
    return dx, dwte.astype(wte.dtype), None


fused_cross_entropy.defvjp(
    lambda x, wte, t, nc: _fwd(x, wte, t, nc), _bwd
)
