"""Flash attention for TPU in Pallas (forward + backward).

Replaces the reference's flash-attn integration — the CUDA wheels and
version-patched modules of atorch/modules/transformer/layers.py:94-182
and the CPU FMHA custom op of tfplus/tfplus/flash_attn/kernels/ — with
one Pallas kernel family designed for the MXU:

* O(T) memory: scores never materialize in HBM; online softmax keeps a
  running (max, sum, acc) per query block in VMEM scratch that persists
  across the sequential kv grid dimension.
* bf16 inputs feed the 128x128 MXU; all softmax statistics and
  accumulators are float32.
* causal masking skips fully-masked kv blocks (no MXU work issued).
* backward is recompute-based (flash-attn v2 style): forward saves only
  the logsumexp; backward runs two kernels (dkv over kv-major grid, dq
  over q-major grid) using delta = rowsum(dO * O) precomputed by XLA.

Layout contract: public API takes [batch, seq, heads, head_dim] (the
model layout of models/gpt.py); kernels operate on [batch*heads, seq,
head_dim]. On non-TPU backends kernels run in interpreter mode so the
same code path is unit-testable on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params(semantics):
    try:
        return pltpu.CompilerParams(dimension_semantics=semantics)
    except TypeError:  # older/newer API without dimension_semantics
        return pltpu.CompilerParams()


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    num_kv: int,
    seq_len: int,
):
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: kv block strictly in the future of every query -> skip.
    first_masked = (jk * block_k) > (iq * block_q + block_q - 1)
    run = jnp.logical_not(jnp.logical_and(causal, first_masked))

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        s = s * scale
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len  # key padding (pad rows contribute 0)
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]  # (block_q, 128) lane-replicated
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        p = jnp.where(mask, p, 0.0)
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jk == num_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.maximum(l, 1e-30)  # fully-masked rows (padding)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse stored as a [block_q, 1] column: native sublane layout,
        # read back broadcast-ready in the backward kernels.
        lse_ref[0] = m_scr[:, :1] + jnp.log(l_safe)


def _fwd(q, k, v, causal, scale, block_q, block_k, seq_len, interpret):
    """q/k/v: [BH, T, D] (T padded to block multiple). Returns (o, lse).
    ``seq_len`` is the true (pre-padding) length: keys beyond it are
    masked out."""
    bh, t, d = q.shape
    num_q = t // block_q
    num_kv = t // block_k
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_kv=num_kv,
        seq_len=seq_len,
    )
    return pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dk_ref,
    dv_ref,
    dk_scr,
    dv_scr,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    num_q: int,
    seq_len: int,
):
    jk = pl.program_id(1)  # kv block (grid-major after batch)
    iq = pl.program_id(2)  # q block (sequential/innermost)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    skip = (jk * block_k) > (iq * block_q + block_q - 1)
    run = jnp.logical_not(jnp.logical_and(causal, skip))

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        lse = lse_ref[0]  # (block_q, 1)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        # dV += P^T dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dP = dO V^T ; dS = P * (dP - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0]
        ds = p * (dp - delta) * scale
        # dK += dS^T Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,
    dq_scr,
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    num_kv: int,
    seq_len: int,
):
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    skip = (jk * block_k) > (iq * block_q + block_q - 1)
    run = jnp.logical_not(jnp.logical_and(causal, skip))

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_len
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        lse = lse_ref[0]
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0]
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jk == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(
    q, k, v, o, lse, do, causal, scale, block_q, block_k, seq_len, interpret
):
    bh, t, d = q.shape
    num_q = t // block_q
    num_kv = t // block_k
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32),
        axis=-1,
        keepdims=True,
    )  # [BH, T, 1]; XLA fuses this rowsum

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_q=num_q,
        seq_len=seq_len,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dq_kernel = functools.partial(
        _bwd_dq_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_kv=num_kv,
        seq_len=seq_len,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing on the [BH, T, D] layout
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, seq_len, interpret):
    o, _ = _fwd(q, k, v, causal, scale, block_q, block_k, seq_len, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, seq_len, interpret):
    o, lse = _fwd(
        q, k, v, causal, scale, block_q, block_k, seq_len, interpret
    )
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, seq_len, interpret, res, g):
    q, k, v, o, lse = res
    return _bwd(
        q, k, v, o, lse, g, causal, scale, block_q, block_k, seq_len,
        interpret,
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention on [batch, seq, heads, head_dim] inputs.

    Drop-in for models.gpt._default_attention. Pads seq to a block
    multiple internally (padded keys are masked, padded query rows are
    sliced off). Runs interpreted off-TPU so tests exercise the same
    kernel on CPU.
    """
    if interpret is None:
        interpret = _use_interpret()
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    block_q = min(block_q, max(t, 8))
    block_k = min(block_k, max(t, 8))

    # Pad so the padded length is divisible by BOTH block sizes (lcm),
    # otherwise the floor-divided grid would silently drop tail blocks.
    import math

    pad = (-t) % math.lcm(block_q, block_k)

    def to_kernel_layout(x):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, d)
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    qk, kk, vk = map(to_kernel_layout, (q, k, v))
    o = _flash(qk, kk, vk, causal, scale, block_q, block_k, t, interpret)
    o = o[:, :t].reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return o.astype(q.dtype)
