"""Flash attention for TPU in Pallas (forward + backward).

Replaces the reference's flash-attn integration — the CUDA wheels and
version-patched modules of atorch/modules/transformer/layers.py:94-182
and the CPU FMHA custom op of tfplus/tfplus/flash_attn/kernels/ — with
one Pallas kernel family designed for the MXU:

* O(T) memory: scores never materialize in HBM; online softmax keeps a
  running (max, sum, acc) per query block in VMEM scratch that persists
  across the sequential kv grid dimension.
* layout-native: kernels block directly over the model's
  [batch, seq, heads, head_dim] arrays (grid over batch x heads), so no
  HBM transpose/reshape passes are spent on either side of the call —
  measured ~0.7ms/layer of pure relayout traffic saved at GPT-2 size.
* bf16 inputs feed the 128x128 MXU; all softmax statistics and
  accumulators are float32; stats are [block_q, 1] columns (one lane),
  not lane-replicated tiles.
* causal masking skips fully-masked kv blocks (no MXU work issued) and
  only diagonal-crossing blocks pay for mask generation at all —
  interior blocks run a maskless fast path (softmax bookkeeping is
  VPU-bound; the lower triangle is dominated by interior blocks).
* backward is recompute-based (flash-attn v2 style) but FUSED: one
  kernel computes dq, dk and dv in a single sweep, recomputing p once
  per (kv, q) block pair instead of once per output operand. dk/dv
  accumulate in block scratch; dq accumulates in a full-sequence VMEM
  scratch (seq * head_dim * 4B — 256KB at 1k context, still only 8MB
  at 32k) flushed once at the end of each (batch, head) slice.
  delta = rowsum(dO * O) is precomputed by XLA.

On non-TPU backends kernels run in interpreter mode so the same code
path is unit-testable on CPU.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params(semantics):
    # Newer pallas spells it CompilerParams; 0.4.x-era jaxlib (this
    # container) still calls it TPUCompilerParams.
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    try:
        return cls(dimension_semantics=semantics)
    except TypeError:  # older/newer API without dimension_semantics
        return cls()


def _block_mask(iq, jk, block_q, block_k, causal, seq_len, pad,
                window, q_offset=0):
    """Mask for block (iq, jk) — only called for blocks that cross the
    diagonal, the sliding-window band edge, or the padding edge;
    interior blocks never generate iotas/compares. ``q_offset``
    (static) shifts q rows to their global positions — the
    rectangular case where q is a chunk of a longer sequence
    (chunked prefill, prefix-LM suffix rows); 0 for square calls."""
    q_pos = q_offset + iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = None
    if pad:
        mask = k_pos < seq_len  # key padding (pad rows contribute 0)
    if causal:
        cm = q_pos >= k_pos
        mask = cm if mask is None else jnp.logical_and(mask, cm)
    if window is not None:
        # Sliding window: query i sees keys (i-window, i] — `window`
        # keys including itself (Mistral convention).
        wm = (q_pos - k_pos) < window
        mask = wm if mask is None else jnp.logical_and(mask, wm)
    return mask


def _dispatch_block(iq, jk, accumulate, *, causal, pad, block_q,
                    block_k, seq_len, window, q_offset=0):
    """Run ``accumulate(masked=...)`` for block (iq, jk), skipping
    fully-future causal blocks and blocks entirely below the sliding
    window band, masking only blocks that cross the diagonal, the
    band edge, or the padding edge — so windowed attention does
    O(T*window) MXU work, not O(T^2). ``q_offset`` shifts q rows to
    global positions (rectangular calls); 0 for square."""
    if not causal and not pad and window is None:
        accumulate(masked=False)
        return
    q0 = q_offset + iq * block_q  # first row's global position
    if causal:
        run = (jk * block_k) <= (q0 + block_q - 1)
        crosses_diag = (jk * block_k + block_k - 1) > q0
    else:
        run = True
        crosses_diag = False
    crosses_pad = ((jk * block_k + block_k) > seq_len) if pad else False
    crosses_band = False
    if window is not None:
        # Lowest visible key for any row in this q block is
        # q0 - window + 1 (the FIRST row's band start); the
        # block is dead when even its last key is below that.
        run = jnp.logical_and(
            run,
            (jk * block_k + block_k - 1) >= (q0 - window + 1),
        )
        # The LAST row's band start is the highest; any key below it
        # needs the element mask.
        crosses_band = (
            (jk * block_k)
            < (q0 + block_q - 1 - window + 1)
        )
    needs_mask = jnp.logical_and(
        run,
        jnp.logical_or(
            jnp.logical_or(crosses_diag, crosses_pad), crosses_band
        ),
    )
    fast = jnp.logical_and(run, jnp.logical_not(needs_mask))

    @pl.when(fast)
    def _fast():
        accumulate(masked=False)

    @pl.when(needs_mask)
    def _masked():
        accumulate(masked=True)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,      # (1, 1, block_q, d)
    k_ref,      # (1, 1, block_k, d)
    v_ref,
    o_ref,      # (1, 1, block_q, d)
    lse_ref,    # (1, 1, block_q, 1)
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    causal: bool,
    window,
    block_q: int,
    block_k: int,
    num_kv: int,
    seq_len: int,
    pad: bool,
    q_offset: int,
):
    iq = pl.program_id(2)
    jk = pl.program_id(3)

    @pl.when(jk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _accumulate(masked: bool):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if scale != 1.0:  # power-of-2 scales are folded into q outside
            s = s * scale
        if masked:
            mask = _block_mask(
                iq, jk, block_q, block_k, causal, seq_len, pad,
                window, q_offset,
            )
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]  # (block_q, 1)
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)  # (block_q, 1): 1-lane exps
        p = jnp.exp(s - m_new)
        if masked and (pad or window is not None):
            # Padding — and sliding windows — can leave a row with no
            # unmasked key in an executed block (m_new = NEG_INF ->
            # exp(0) = 1): under a window, a row's band may start in a
            # later kv block than the first one the block-level skip
            # admits for its q block. Under pure causal masking every
            # executed row has a finite m_new, so exp(NEG_INF - m_new)
            # already underflows to exactly 0 and the select is waste.
            p = jnp.where(mask, p, 0.0)
        l_scr[:] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _dispatch_block(
        iq, jk, _accumulate, causal=causal, pad=pad, block_q=block_q,
        block_k=block_k, seq_len=seq_len, window=window,
        q_offset=q_offset,
    )

    @pl.when(jk == num_kv - 1)
    def _finalize():
        l = l_scr[:]
        l_safe = jnp.maximum(l, 1e-30)  # fully-masked rows (padding)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse stored as a [block_q, 1] column: native sublane layout,
        # read back broadcast-ready in the backward kernel.
        lse_ref[0, 0] = m_scr[:] + jnp.log(l_safe)


def _fwd(q, k, v, causal, window, scale, block_q, block_k, seq_len,
         interpret, q_offset=0):
    """q: [B, H, Tq, D]; k/v: [B, H, Tk, D] (each padded to its block
    multiple — Tq == Tk for the square call). Returns (o [B,H,Tq,D],
    lse [B,H,Tq,1]). ``seq_len`` is the true KEY length: keys beyond
    it are masked out. ``q_offset`` is the global position of q row 0
    (causal/window comparisons happen in key coordinates)."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    num_q = tq // block_q
    num_kv = tk // block_k
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        window=window,
        block_q=block_q,
        block_k=block_k,
        num_kv=num_kv,
        seq_len=seq_len,
        pad=seq_len < tk,
        q_offset=q_offset,
    )
    return pl.pallas_call(
        kernel,
        grid=(b, h, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            ("parallel", "parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
        # Stable identity for jax.checkpoint policies: the save_attn
        # remat policy (accelerate/remat.py) matches this name to
        # save exactly (o, lse) and nothing else Pallas produces.
        name="flash_attention_fwd",
    )(q, k, v)


# ---------------------------------------------------------------------------
# Backward: one fused kernel for dq, dk, dv
# ---------------------------------------------------------------------------


def _bwd_kernel(
    q_ref,      # (1, 1, block_q, d)
    k_ref,      # (1, 1, block_k, d)
    v_ref,
    do_ref,     # (1, 1, block_q, d)
    lse_ref,    # (1, 1, block_q, 1)
    delta_ref,  # (1, 1, block_q, 1)
    dq_ref,     # (1, 1, t, d) — whole-sequence block, written once
    dk_ref,     # (1, 1, block_k, d)
    dv_ref,
    dq_scr,     # (t, d) f32 — full-sequence accumulator
    dk_scr,
    dv_scr,
    *,
    scale: float,
    causal: bool,
    window,
    block_q: int,
    block_k: int,
    num_q: int,
    num_kv: int,
    seq_len: int,
    pad: bool,
    q_offset: int,
):
    jk = pl.program_id(2)  # kv block (outer)
    iq = pl.program_id(3)  # q block (inner)

    @pl.when(jnp.logical_and(jk == 0, iq == 0))
    def _init_dq():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(iq == 0)
    def _init_dkv():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _accumulate(masked: bool):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if scale != 1.0:
            s = s * scale
        lse = lse_ref[0, 0]  # (block_q, 1)
        p = jnp.exp(s - lse)
        if masked:
            mask = _block_mask(
                iq, jk, block_q, block_k, causal, seq_len, pad,
                window, q_offset,
            )
            p = jnp.where(mask, p, 0.0)
        pt = p.astype(do.dtype)
        # dV += P^T dO
        dv_scr[:] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dP = dO V^T ; dS = P * (dP - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        delta = delta_ref[0, 0]
        # delta folds BOTH cotangents: rowsum(dO*O) from the output
        # and -g_lse from the logsumexp (dlse/ds_j = p_j), see _bwd.
        ds = p * (dp - delta)
        if scale != 1.0:
            ds = ds * scale
        ds = ds.astype(q.dtype)
        # dK += dS^T Q
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dQ[iq] += dS K — accumulated across the outer kv loop in the
        # full-sequence scratch (no second recompute pass).
        sl = pl.dslice(iq * block_q, block_q)
        dq_scr[sl, :] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _dispatch_block(
        iq, jk, _accumulate, causal=causal, pad=pad, block_q=block_q,
        block_k=block_k, seq_len=seq_len, window=window,
        q_offset=q_offset,
    )

    @pl.when(iq == num_q - 1)
    def _flush_dkv():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)

    @pl.when(jnp.logical_and(jk == num_kv - 1, iq == num_q - 1))
    def _flush_dq():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(
    q, k, v, o, lse, do, causal, window, scale, block_q, block_k,
    seq_len, interpret, g_lse=None, q_offset=0,
):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    num_q = tq // block_q
    num_kv = tk // block_k
    pad = seq_len < tk
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32),
        axis=-1,
        keepdims=True,
    )  # [B, H, T, 1]; XLA fuses this rowsum
    if g_lse is not None:
        # lse cotangent: dlse/ds_j = p_j, so dS gains p * g_lse — the
        # same rank-1 shape as the delta term, folded in host-side.
        delta = delta - g_lse

    kernel = functools.partial(
        _bwd_kernel,
        scale=scale,
        causal=causal,
        window=window,
        block_q=block_q,
        block_k=block_k,
        num_q=num_q,
        num_kv=num_kv,
        seq_len=seq_len,
        pad=pad,
        q_offset=q_offset,
    )
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b, h, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, j, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, j, i: (b, h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, tq, d), lambda b, h, j, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tq, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing on the [B, H, T, D] layout
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12)
)
def _flash(q, k, v, causal, window, scale, block_q, block_k,
           block_q_bwd, block_k_bwd, seq_len, interpret, q_offset=0):
    o, _ = _fwd(q, k, v, causal, window, scale, block_q, block_k,
                seq_len, interpret, q_offset)
    return o


def _flash_fwd(q, k, v, causal, window, scale, block_q, block_k,
               block_q_bwd, block_k_bwd, seq_len, interpret,
               q_offset=0):
    o, lse = _fwd(
        q, k, v, causal, window, scale, block_q, block_k, seq_len,
        interpret, q_offset
    )
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, window, scale, block_q, block_k, block_q_bwd,
               block_k_bwd, seq_len, interpret, q_offset, res, g):
    q, k, v, o, lse = res
    return _bwd(
        q, k, v, o, lse, g, causal, window, scale, block_q_bwd,
        block_k_bwd, seq_len, interpret, q_offset=q_offset,
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12)
)
def _flash_lse(q, k, v, causal, window, scale, block_q, block_k,
               block_q_bwd, block_k_bwd, seq_len, interpret,
               q_offset=0):
    """Like _flash but also returns the per-row logsumexp — the
    ingredient ring attention needs to merge normalized block outputs
    across devices (parallel/ring_attention.py)."""
    return _fwd(
        q, k, v, causal, window, scale, block_q, block_k, seq_len,
        interpret, q_offset
    )


def _flash_lse_fwd(q, k, v, causal, window, scale, block_q, block_k,
                   block_q_bwd, block_k_bwd, seq_len, interpret,
                   q_offset=0):
    o, lse = _fwd(
        q, k, v, causal, window, scale, block_q, block_k, seq_len,
        interpret, q_offset
    )
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_bwd(causal, window, scale, block_q, block_k,
                   block_q_bwd, block_k_bwd, seq_len, interpret,
                   q_offset, res, g):
    g_o, g_lse = g
    q, k, v, o, lse = res
    return _bwd(
        q, k, v, o, lse, g_o, causal, window, scale, block_q_bwd,
        block_k_bwd, seq_len, interpret, g_lse=g_lse,
        q_offset=q_offset,
    )


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _check_block_chain(blocks, t: int) -> int:
    """lcm of ``blocks``, rejecting sets whose combined lcm would
    materially inflate the padded sequence. Divisibility-chain-ish
    sets (lcm <= 2*max) always pass; a coprime set passes only when
    the padding it actually forces at this ``t`` stays under one
    max-block of slack — so tuned configs where t already divides the
    lcm keep working, while e.g. bq=512/bqb=384 at t=520 (pad to
    1536, ~3x kernel work) are rejected."""
    lcm = math.lcm(*blocks)
    if lcm > 2 * max(blocks) and (-t) % lcm >= max(blocks):
        raise ValueError(
            f"block sizes {tuple(blocks)} are too coprime at t={t}: "
            f"padding to their lcm ({lcm}) would inflate the "
            "sequence for every kernel, not just the one being tuned "
            "— pick sizes that divide one another"
        )
    return lcm


def default_block_sizes(t: int) -> tuple:
    """Autotuned (block_q, block_k) by sequence length (measured on
    v5e, GPT-2 train step): 512 blocks beat 128 by ~2.5x at T=1024
    (fewer grid steps, less per-block softmax bookkeeping), and the
    r4 sweep (tools/autotune_bwd_blocks.py + perf_sweep) moved the
    optimum to 1024x1024 — 158.8 ms vs 165.2 ms at 512x1024 on the
    16x1024 step (fused norms off in both), 0.902 vs 0.867
    vs_baseline. The f32 score tile is
    [block_q, block_k] (4 MB at 1024x1024), VMEM-safe alongside the
    q/k/v/o blocks at head dims up to 128. Below 1024 context the
    block covers the sequence; block_k doubles only when the
    sequence is a multiple of 2*block_q — otherwise unequal blocks
    would pad to lcm(block_q, block_k), which explodes for lengths
    like 520 (lcm(512, 520) = 33280)."""
    if t % 1024 == 0:
        # The measured r4 optimum — only where it costs no padding
        # (t=1536 would pad to 2048, +33% kernel work; t=516 would
        # yield a sublane-misaligned 516 block).
        return 1024, 1024
    bq = max(min(512, t), 8)
    bk = 2 * bq if t % (2 * bq) == 0 else bq
    return bq, bk


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_q_bwd: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    interpret: Optional[bool] = None,
    return_lse: bool = False,
    window: Optional[int] = None,
) -> "jax.Array | tuple[jax.Array, jax.Array]":
    """Flash attention on [batch, seq, heads, head_dim] inputs.

    Drop-in for models.gpt._default_attention. The [B,H,T,D] kernel
    layout transposes sit OUTSIDE the pallas_call so XLA can fuse them
    into the neighbouring projection matmuls. Pads seq to a block
    multiple internally (padded keys are masked, padded query rows are
    sliced off). Runs interpreted off-TPU so tests exercise the same
    kernel on CPU.

    ``return_lse=True`` also returns the per-row logsumexp [B, H, T]
    (f32, differentiable) — used by ring attention to merge block
    outputs across devices.

    ``block_q_bwd``/``block_k_bwd`` tune the backward kernel's blocks
    independently of the forward's (they default to the forward
    blocks); the backward's access pattern (kv-outer grid, dq
    full-sequence scratch) can favor different tiles.

    ``window`` enables Mistral-style sliding-window attention: query
    i attends to keys (i-window, i], and kv blocks entirely below the
    band are skipped — O(T*window) MXU work instead of O(T^2).
    Requires ``causal=True``.
    """
    if interpret is None:
        interpret = _use_interpret()
    b, t, h, d = q.shape
    if window is not None:
        if not causal:
            raise ValueError(
                "window (sliding-window attention) requires causal=True"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window >= t:
            window = None  # band covers the whole sequence: plain causal
    if scale is None:
        scale = 1.0 / (d**0.5)
    # Power-of-2 scales (every power-of-4 head_dim, e.g. 64 -> 1/8)
    # multiply exactly in any float dtype, so fold them into q outside
    # the kernel: XLA fuses the multiply into the surrounding
    # transpose/pad, the kernel's `s * scale` pass over each
    # [block_q, block_k] tile disappears (scale==1.0 folds at trace
    # time), and autodiff routes the q-gradient scale through this
    # multiply.
    if scale != 1.0 and math.frexp(scale)[0] == 0.5:
        q = q * jnp.asarray(scale, q.dtype)
        scale = 1.0
    # A requested block larger than the sequence means "one tile
    # spanning the whole (padded) sequence". Clamp those to the padded
    # length implied by the in-range blocks — that adds no padding and
    # always satisfies the divisibility-chain guard below, unlike
    # clamping to t itself (block_k=1024 at t=520 -> 520 used to trip
    # the guard for a call that tuned fine at longer sequences).
    cap = max(t, 8)
    dq_, dk_ = default_block_sizes(t)
    req_q = dq_ if block_q is None else block_q
    req_k = dk_ if block_k is None else block_k
    req_qb = req_q if block_q_bwd is None else block_q_bwd
    req_kb = req_k if block_k_bwd is None else block_k_bwd
    reqs = (req_q, req_k, req_qb, req_kb)
    in_range = [r for r in reqs if r <= cap]
    # Guard the in-range blocks BEFORE substituting padded_base (a
    # multiple of their lcm): the substitution makes padded_base the
    # max of the final block set, so the post-substitution check alone
    # can never fire for coprime in-range blocks — e.g. bq=512,
    # bqb=384, bk=1024 at t=520 must be rejected, not silently padded
    # 520 -> 1536 (~3x kernel work).
    unit = _check_block_chain(in_range, t) if in_range else 1
    padded_base = max(8, math.ceil(t / unit) * unit)
    block_q, block_k, block_q_bwd, block_k_bwd = (
        r if r <= cap else padded_base for r in reqs
    )

    # Pad so the padded length is divisible by EVERY block size (lcm),
    # otherwise the floor-divided grids would silently drop tail
    # blocks. Inflation protection lives entirely in the
    # pre-substitution check above: after substitution padded_base is
    # a multiple of lcm(in_range) and the max of the set, so this lcm
    # equals padded_base (or lcm(in_range) when nothing was
    # substituted) and cannot explode.
    blocks = (block_q, block_k, block_q_bwd, block_k_bwd)
    pad = (-t) % math.lcm(*blocks)

    def to_kernel_layout(x):
        x = jnp.transpose(x, (0, 2, 1, 3))  # [B,H,T,D]
        if pad:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        return x

    qk, kk, vk = map(to_kernel_layout, (q, k, v))
    if return_lse:
        o, lse = _flash_lse(
            qk, kk, vk, causal, window, scale, block_q, block_k,
            block_q_bwd, block_k_bwd, t, interpret,
        )
        o = o[:, :, :t].transpose(0, 2, 1, 3)
        return o.astype(q.dtype), lse[:, :, :t, 0]
    o = _flash(
        qk, kk, vk, causal, window, scale, block_q, block_k,
        block_q_bwd, block_k_bwd, t, interpret,
    )
    o = o[:, :, :t].transpose(0, 2, 1, 3)
    return o.astype(q.dtype)


def flash_attention_rect(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: Optional[int] = None,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_q_bwd: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
    interpret: Optional[bool] = None,
    return_lse: bool = False,
    window: Optional[int] = None,
) -> "jax.Array | tuple[jax.Array, jax.Array]":
    """Rectangular flash attention: q [B, Tq, H, D] against
    k/v [B, Tk, H, D] with Tq != Tk allowed.

    ``q_offset`` is the global position of q row 0 in key
    coordinates: causal means q row i attends keys j <= q_offset + i.
    Defaults to ``Tk - Tq`` — "the queries are the LAST Tq positions
    of the key sequence", the chunked-prefill convention (a decode
    chunk attends the whole cache causally). Pass 0 for "queries
    start at key 0".

    Use cases this unlocks at exact cost (no redundant square rows):

    * chunked prefill — long prompts prefilled in bounded-memory
      query chunks against the growing cache;
    * prefix-LM suffix rows (ops/prefix_lm.py) — suffix queries
      against the full sequence without recomputing prefix rows;
    * cross-attention — ``causal=False`` with any Tq/Tk.

    Each side pads independently to its own block multiples; padded
    keys are masked via the true key length, padded q rows are
    sliced off. Gradients flow to q, k and v (same fused backward,
    rectangular grid). For Tq == Tk with q_offset == 0, prefer the
    square :func:`flash_attention` (same kernels, tuned defaults).
    """
    if interpret is None:
        interpret = _use_interpret()
    b, tq0, h, d = q.shape
    tk0 = k.shape[1]
    if q_offset is None:
        q_offset = tk0 - tq0
    if causal and q_offset < 0:
        raise ValueError(
            f"causal rectangular attention needs q_offset >= 0 "
            f"(got {q_offset}): q rows before key 0 would attend "
            "nothing"
        )
    if window is not None:
        # The band compares run in key coordinates with the same
        # q_offset shift as the causal compare — Mistral chunked
        # prefill: each chunk does O(chunk * window) work, dead kv
        # blocks below the band skipped.
        if not causal:
            raise ValueError(
                "window (sliding-window attention) requires "
                "causal=True"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window > q_offset + tq0:
            window = None  # band covers every visible key
    if scale is None:
        scale = 1.0 / (d**0.5)
    if scale != 1.0 and math.frexp(scale)[0] == 0.5:
        q = q * jnp.asarray(scale, q.dtype)
        scale = 1.0

    # Per-side blocks: q-side sizes bound by Tq, k-side by Tk. Same
    # rules as the square wrapper, applied per side: requests larger
    # than the side substitute the padded base (so tuned configs that
    # work on the square kernel keep working here), the coprime guard
    # runs on the in-range requests, and every final block is rounded
    # up to the 8-sublane tile (short suffixes like Tq=23 would
    # otherwise emit an unloweable 23-row block; the round-up costs
    # at most 7 pad rows).
    def side(req, req_bwd, t, which):
        cap = max(t, 8)
        dflt = default_block_sizes(t)[which]
        # Round to the 8-sublane tile BEFORE the coprime guard — the
        # guard must judge the blocks that actually pad, or rounding
        # could silently reintroduce the inflation it rejects (e.g.
        # 24/12 -> 24/16, lcm 24 -> 48).
        r1 = -(-(req or dflt) // 8) * 8
        r2 = -(-(req_bwd or req or dflt) // 8) * 8
        in_range = [r for r in (r1, r2) if r <= cap]
        unit = _check_block_chain(in_range, t) if in_range else 1
        padded_base = -(-max(8, math.ceil(t / unit) * unit) // 8) * 8
        return tuple(
            r if r <= cap else padded_base for r in (r1, r2)
        )

    bq, bqb = side(block_q, block_q_bwd, tq0, 0)
    bk, bkb = side(block_k, block_k_bwd, tk0, 1)
    pad_q = (-tq0) % math.lcm(bq, bqb)
    pad_k = (-tk0) % math.lcm(bk, bkb)

    def to_kernel(x, pad):
        x = jnp.transpose(x, (0, 2, 1, 3))
        if pad:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        return x

    qk = to_kernel(q, pad_q)
    kk_, vk = to_kernel(k, pad_k), to_kernel(v, pad_k)
    if return_lse:
        o, lse = _flash_lse(
            qk, kk_, vk, causal, window, scale, bq, bk, bqb, bkb,
            tk0, interpret, q_offset,
        )
        o = o[:, :, :tq0].transpose(0, 2, 1, 3)
        return o.astype(q.dtype), lse[:, :, :tq0, 0]
    o = _flash(
        qk, kk_, vk, causal, window, scale, bq, bk, bqb, bkb,
        tk0, interpret, q_offset,
    )
    return o[:, :, :tq0].transpose(0, 2, 1, 3).astype(q.dtype)


def blocks_kwargs(attn_blocks: Optional[tuple]) -> dict:
    """(bq, bk, bqb, bkb) config tuple -> flash call kwargs — the one
    definition of the ``attn_blocks`` contract (model configs carry
    the tuple; gpt.default_attention_for and ops/prefix_lm.py unpack
    it through here)."""
    if attn_blocks is None:
        return {}
    bq, bk, bqb, bkb = attn_blocks
    return dict(
        block_q=bq, block_k=bk, block_q_bwd=bqb, block_k_bwd=bkb
    )
