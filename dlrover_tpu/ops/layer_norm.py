"""Fused LayerNorm / RMSNorm (+ residual add) in Pallas, fwd + bwd.

Parity target: the reference integrates fused
``dropout_add_layer_norm`` CUDA kernels
(atorch/modules/transformer/layers.py:74) and a fused LayerNorm module
(atorch/normalization/) because norms sit on the HBM-bound residual
spine of every transformer block. The TPU version fuses the residual
add into the norm so the pre-norm branch point writes/reads HBM once:

    out, resid = fused_layer_norm(x, g, b, residual=res)
      resid = x + res   (the next branch point, saved for backward)
      out   = (resid - mu) * rsqrt(var + eps) * g + b

* one row-blocked kernel per pass; statistics in f32 at [rows, 1]
  (single lane), activations any float dtype;
* backward is a single kernel producing dx and per-row-block PARTIAL
  dg/db tiles (cross-row reductions), summed by XLA outside — the
  partials are tiny [n_blocks, E] f32;
* dropout is intentionally NOT fused: elastic-training configs run
  dropout 0 (nanoGPT parity, models/gpt.py), so the fusion the
  reference needs for torch dropout is dead weight here.

On non-TPU backends the kernels run in interpreter mode (same code
path, unit-testable on CPU) — but callers (models/gpt.py,
models/llama.py) auto-select the plain XLA norm off-TPU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dlrover_tpu.ops.flash_attention import (
    _compiler_params,
    _use_interpret,
)

DEFAULT_BLOCK_ROWS = 256
# Per-ref VMEM budget for a [block_rows, E] f32 block. The backward
# kernel keeps ~6 such refs live per grid step, so 1 MiB/ref stays
# well under the ~16 MiB/core VMEM even before double-buffering.
_ROW_BLOCK_BYTE_BUDGET = 1 << 20


def pick_block_rows(e: int) -> int:
    """Default row-block for embedding width ``e``: the fixed
    DEFAULT_BLOCK_ROWS while a [rows, e] f32 block fits the byte
    budget, shrinking (multiples of 8) as ``e`` grows so wide models
    (e >= 1024) cannot overflow VMEM."""
    rows = _ROW_BLOCK_BYTE_BUDGET // (max(e, 1) * 4)
    return min(DEFAULT_BLOCK_ROWS, max(8, rows - rows % 8))


def _rows_pad(n: int, block: int) -> int:
    return (-n) % block


# -- forward kernels ----------------------------------------------------


def _fwd_kernel(x_ref, res_ref, g_ref, b_ref, out_ref, resid_ref,
                mu_ref, rstd_ref, *, eps, rms, add_residual):
    x = x_ref[...].astype(jnp.float32)
    if add_residual:
        x = x + res_ref[...].astype(jnp.float32)
    if add_residual:
        resid_ref[...] = x.astype(resid_ref.dtype)
    if rms:
        mu = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    else:
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * rstd
    out = xhat * g_ref[...].astype(jnp.float32)
    if b_ref is not None:
        out = out + b_ref[...].astype(jnp.float32)
    out_ref[...] = out.astype(out_ref.dtype)
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _bwd_kernel(dout_ref, resid_ref, g_ref, mu_ref, rstd_ref,
                dx_ref, dg_ref, db_ref, *, rms):
    dout = dout_ref[...].astype(jnp.float32)
    y = resid_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mu = mu_ref[...]
    rstd = rstd_ref[...]
    xhat = (y - mu) * rstd

    # dg/db partials: one (8, E) accumulator block shared by every
    # grid step (real TPU lowering requires block sublanes divisible
    # by 8 — a (1, E) row per step is not tileable). Sequential
    # "arbitrary" grid semantics keep the block resident, so
    # read-modify-write accumulation is sound (the flash kernel's dkv
    # uses the same pattern); rows reduce 8-wise here and the final
    # 8 -> 1 fold happens host-side.
    @pl.when(pl.program_id(0) == 0)
    def _zero():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        if db_ref is not None:
            db_ref[...] = jnp.zeros_like(db_ref)

    r, e = dout.shape
    dg_ref[...] += jnp.sum(
        (dout * xhat).reshape(r // 8, 8, e), axis=0
    )
    if db_ref is not None:
        db_ref[...] += jnp.sum(dout.reshape(r // 8, 8, e), axis=0)
    wdout = dout * g
    c2 = jnp.mean(wdout * xhat, axis=-1, keepdims=True)
    if rms:
        dx = (wdout - xhat * c2) * rstd
    else:
        c1 = jnp.mean(wdout, axis=-1, keepdims=True)
        dx = (wdout - c1 - xhat * c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)


# -- host-side wrappers -------------------------------------------------


def _fwd(x2, res2, g, b, *, eps, rms, block_rows, interpret):
    n, e = x2.shape
    pad = _rows_pad(n, block_rows)
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        if res2 is not None:
            res2 = jnp.pad(res2, ((0, pad), (0, 0)))
    rows = x2.shape[0]
    grid = (rows // block_rows,)
    row_spec = pl.BlockSpec((block_rows, e), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((block_rows, 1), lambda i: (i, 0))
    gb_spec = pl.BlockSpec((1, e), lambda i: (0, 0))
    add_residual = res2 is not None

    in_specs = [row_spec]
    inputs = [x2]
    if add_residual:
        in_specs.append(row_spec)
        inputs.append(res2)
    in_specs.append(gb_spec)
    inputs.append(g.reshape(1, e))
    if b is not None:
        in_specs.append(gb_spec)
        inputs.append(b.reshape(1, e))

    kernel = functools.partial(
        _kernel_fwd_dispatch,
        eps=eps,
        rms=rms,
        add_residual=add_residual,
        has_bias=b is not None,
    )
    # The resid output only exists on the add path: callers of the
    # plain norm already hold x, so emitting x again would add a dead
    # full-tensor HBM write to the exact spine this kernel relieves.
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, e), x2.dtype)]
    if add_residual:
        out_specs.append(row_spec)
        out_shape.append(jax.ShapeDtypeStruct((rows, e), x2.dtype))
    out_specs += [stat_spec, stat_spec]
    out_shape += [
        jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        jax.ShapeDtypeStruct((rows, 1), jnp.float32),
    ]
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(("arbitrary",)),
        interpret=interpret,
    )(*inputs)
    if add_residual:
        out, resid, mu, rstd = outs
        return out[:n], resid[:n], mu, rstd
    out, mu, rstd = outs
    return out[:n], None, mu, rstd


def _kernel_fwd_dispatch(*refs, eps, rms, add_residual, has_bias):
    """Unpack the variadic ref list into the named kernel args."""
    i = 0
    x_ref = refs[i]; i += 1
    res_ref = None
    if add_residual:
        res_ref = refs[i]; i += 1
    g_ref = refs[i]; i += 1
    b_ref = None
    if has_bias:
        b_ref = refs[i]; i += 1
    out_ref = refs[i]; i += 1
    resid_ref = None
    if add_residual:
        resid_ref = refs[i]; i += 1
    mu_ref, rstd_ref = refs[i:i + 2]
    _fwd_kernel(
        x_ref, res_ref, g_ref, b_ref, out_ref, resid_ref, mu_ref,
        rstd_ref, eps=eps, rms=rms, add_residual=add_residual,
    )


def _bwd(dout2, resid2, g, mu, rstd, *, rms, has_bias, block_rows,
         interpret):
    if block_rows % 8:
        raise ValueError(
            f"block_rows={block_rows} must be a multiple of 8 (the "
            "f32 sublane tile; the dg/db partial accumulator reduces "
            "rows 8-wise)"
        )
    n, e = dout2.shape
    pad = _rows_pad(n, block_rows)
    if pad:
        dout2 = jnp.pad(dout2, ((0, pad), (0, 0)))
        resid2 = jnp.pad(resid2, ((0, pad), (0, 0)))
        # rstd pad rows are zero -> their dx rows compute to 0.
        mu = jnp.pad(mu, ((0, pad), (0, 0)))
        rstd = jnp.pad(rstd, ((0, pad), (0, 0)))
    rows = dout2.shape[0]
    nblocks = rows // block_rows
    grid = (nblocks,)
    row_spec = pl.BlockSpec((block_rows, e), lambda i: (i, 0))
    stat_spec = pl.BlockSpec((block_rows, 1), lambda i: (i, 0))
    gb_spec = pl.BlockSpec((1, e), lambda i: (0, 0))
    # Every grid step accumulates into the SAME (8, e) partial block
    # (see _bwd_kernel): 8 sublanes is the minimum f32 tile height on
    # real TPU, so per-block (1, e) rows would not lower.
    part_spec = pl.BlockSpec((8, e), lambda i: (0, 0))

    out_specs = [row_spec, part_spec]
    out_shape = [
        jax.ShapeDtypeStruct((rows, e), dout2.dtype),
        jax.ShapeDtypeStruct((8, e), jnp.float32),
    ]
    if has_bias:
        out_specs.append(part_spec)
        out_shape.append(
            jax.ShapeDtypeStruct((8, e), jnp.float32)
        )

    def kernel(dout_ref, resid_ref, g_ref, mu_ref, rstd_ref, *outs):
        dx_ref = outs[0]
        dg_ref = outs[1]
        db_ref = outs[2] if has_bias else None
        _bwd_kernel(
            dout_ref, resid_ref, g_ref, mu_ref, rstd_ref,
            dx_ref, dg_ref, db_ref, rms=rms,
        )

    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, gb_spec, stat_spec, stat_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(("arbitrary",)),
        interpret=interpret,
    )(dout2, resid2, g.reshape(1, e), mu, rstd)
    dx = outs[0][:n]
    dg = jnp.sum(outs[1], axis=0)
    db = jnp.sum(outs[2], axis=0) if has_bias else None
    return dx, dg, db


# -- public API (custom VJP) -------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _norm(x, g, b, eps, rms, block_rows, interpret):
    out, _ = _norm_fwd(x, g, b, eps, rms, block_rows, interpret)
    return out


def _norm_fwd(x, g, b, eps, rms, block_rows, interpret):
    shape = x.shape
    e = shape[-1]
    x2 = x.reshape(-1, e)
    n = x2.shape[0]
    out, _, mu, rstd = _fwd(
        x2, None, g, b, eps=eps, rms=rms, block_rows=block_rows,
        interpret=interpret,
    )
    saved = (x2, g, mu[:n], rstd[:n], b is not None, shape)
    return out.reshape(shape), saved


def _norm_bwd(eps, rms, block_rows, interpret, saved, dout):
    x2, g, mu, rstd, has_bias, shape = saved
    e = shape[-1]
    dx, dg, db = _bwd(
        dout.reshape(-1, e), x2, g, mu, rstd, rms=rms,
        has_bias=has_bias, block_rows=block_rows,
        interpret=interpret,
    )
    return (
        dx.reshape(shape),
        dg.astype(g.dtype),
        db.astype(g.dtype) if has_bias else None,
    )


_norm.defvjp(_norm_fwd, _norm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _add_norm(x, res, g, b, eps, rms, block_rows, interpret):
    outs, _ = _add_norm_fwd(
        x, res, g, b, eps, rms, block_rows, interpret
    )
    return outs


def _add_norm_fwd(x, res, g, b, eps, rms, block_rows, interpret):
    shape = x.shape
    e = shape[-1]
    out, resid2, mu, rstd = _fwd(
        x.reshape(-1, e), res.reshape(-1, e), g, b, eps=eps,
        rms=rms, block_rows=block_rows, interpret=interpret,
    )
    n = out.shape[0]
    saved = (resid2, g, mu[:n], rstd[:n], b is not None, shape)
    return (out.reshape(shape), resid2.reshape(shape)), saved


def _add_norm_bwd(eps, rms, block_rows, interpret, saved, cots):
    dout, dresid = cots
    resid2, g, mu, rstd, has_bias, shape = saved
    e = shape[-1]
    dy, dg, db = _bwd(
        dout.reshape(-1, e), resid2, g, mu, rstd, rms=rms,
        has_bias=has_bias, block_rows=block_rows,
        interpret=interpret,
    )
    # y = x + res feeds both the norm and (via the second output) the
    # rest of the network: total dy adds the downstream cotangent.
    dy = dy.reshape(shape) + dresid
    return (
        dy,
        dy,
        dg.astype(g.dtype),
        db.astype(g.dtype) if has_bias else None,
    )


_add_norm.defvjp(_add_norm_fwd, _add_norm_bwd)


def fused_layer_norm(
    x: jax.Array,
    g: jax.Array,
    b: Optional[jax.Array] = None,
    eps: float = 1e-5,
    block_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """LayerNorm over the last axis, f32 statistics, any float input
    dtype. Differentiable (custom VJP, single fused backward kernel).
    """
    if interpret is None:
        interpret = _use_interpret()
    if block_rows is None:
        block_rows = pick_block_rows(x.shape[-1])
    return _norm(x, g, b, eps, False, block_rows, interpret)


def fused_rms_norm(
    x: jax.Array,
    g: jax.Array,
    eps: float = 1e-6,
    block_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """RMSNorm over the last axis (Llama family)."""
    if interpret is None:
        interpret = _use_interpret()
    if block_rows is None:
        block_rows = pick_block_rows(x.shape[-1])
    return _norm(x, g, None, eps, True, block_rows, interpret)


def fused_add_layer_norm(
    x: jax.Array,
    residual: jax.Array,
    g: jax.Array,
    b: Optional[jax.Array] = None,
    eps: float = 1e-5,
    block_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(norm(x + residual), x + residual) with the add fused into the
    norm kernel — the pre-norm residual branch point in one HBM pass
    (the reference's dropout_add_layer_norm at dropout 0,
    atorch/modules/transformer/layers.py:74). The second output is
    the input to the NEXT residual add.
    """
    if interpret is None:
        interpret = _use_interpret()
    if block_rows is None:
        block_rows = pick_block_rows(x.shape[-1])
    return _add_norm(
        x, residual, g, b, eps, False, block_rows, interpret
    )


def fused_add_rms_norm(
    x: jax.Array,
    residual: jax.Array,
    g: jax.Array,
    eps: float = 1e-6,
    block_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(rmsnorm(x + residual), x + residual) — Llama residual spine."""
    if interpret is None:
        interpret = _use_interpret()
    if block_rows is None:
        block_rows = pick_block_rows(x.shape[-1])
    return _add_norm(
        x, residual, g, None, eps, True, block_rows, interpret
    )
