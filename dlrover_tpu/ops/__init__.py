"""TPU kernel library (Pallas).

The TPU-native replacement for the reference's native-op layer:
flash-attention CUDA wheels + patched modules
(atorch/modules/transformer/layers.py), the TF CPU FMHA op
(tfplus/flash_attn/kernels/*), and the CUDA quantization suite
(atorch/ops/csrc/quantization/*). Kernels are written once in Pallas
and run compiled on TPU or interpreted on CPU for tests.
"""

from dlrover_tpu.ops.flash_attention import flash_attention  # noqa: F401
