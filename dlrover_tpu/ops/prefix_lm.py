"""Prefix-LM attention composed from the verified flash kernels.

The GLM family trains with blank-infilling: context tokens (the
"prefix") attend bidirectionally to each other, generated tokens (the
"suffix") attend to the whole prefix plus causally to earlier suffix
tokens (reference: atorch's GLM module stack,
/root/reference/atorch/atorch/modules/distributed_modules/transformer.py,
whose parallel GLM blocks consume exactly this mask through HF GLM's
``get_masks``).

The mask decomposes exactly onto kernels we already trust
(ops/flash_attention.py) with no new masking code:

* a suffix row i >= p attends keys {j <= i} ∪ {j < p} = {j <= i}
  (p <= i makes the prefix part a subset of the causal part) — so
  suffix rows are PURELY CAUSAL rows at their global offset;
* a prefix row i < p attends {j <= i} ∪ {j < p} = {j < p} — full
  bidirectional attention within the square prefix block.

So: one non-causal flash call on the p x p prefix, one RECTANGULAR
causal call (flash_attention_rect, q_offset = p) of the s = t - p
suffix queries against all t keys — exact cost, no redundant prefix
rows. Every FLOP runs inside the flash kernel; the composition is
differentiable through ordinary slicing (dk/dv contributions from
the two calls add where key ranges overlap).

``prefix_len`` is static — under jit each distinct prefix length
compiles once, the XLA-friendly contract (SURVEY.md: no
data-dependent shapes inside jit).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def prefix_lm_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    prefix_len: int,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    attn_blocks: Optional[tuple] = None,
) -> jax.Array:
    """Prefix-LM attention on [B, T, H, D] inputs.

    ``prefix_len`` (static python int) positions attend
    bidirectionally among themselves; the remaining ``T -
    prefix_len`` positions attend to the full prefix and causally
    within the suffix. Degenerate cases delegate straight to the
    flash kernel: ``prefix_len == 0`` is causal attention,
    ``prefix_len == T`` is full bidirectional attention.
    """
    import math

    from dlrover_tpu.ops.flash_attention import (
        blocks_kwargs,
        flash_attention,
        flash_attention_rect,
    )

    b, t, h, d = q.shape
    p = int(prefix_len)
    if not 0 <= p <= t:
        raise ValueError(f"prefix_len={p} outside [0, {t}]")
    if scale is None:
        scale = 1.0 / (d**0.5)
    # Flash tile override (bq, bk, bqb, bkb) — the knob the model
    # configs carry, tuned at the FULL sequence length. The rect
    # suffix call clamps per side itself; the square sub-calls only
    # take the tuning when their local length fits it cleanly
    # (every block <= length and length a multiple of their lcm) —
    # an arbitrary prefix length falls back to the per-length
    # defaults rather than tripping the coprime-inflation guard with
    # tiles the tuning never measured.
    bkw = blocks_kwargs(attn_blocks)

    def square_bkw(length):
        if not bkw:
            return {}
        vals = tuple(bkw.values())
        if max(vals) <= length and length % math.lcm(*vals) == 0:
            return bkw
        return {}

    if p == 0:
        return flash_attention(
            q, k, v, causal=True, scale=scale, interpret=interpret,
            **square_bkw(t),
        )
    if p == t:
        return flash_attention(
            q, k, v, causal=False, scale=scale, interpret=interpret,
            **square_bkw(t),
        )

    o_pre = flash_attention(
        q[:, :p], k[:, :p], v[:, :p], causal=False, scale=scale,
        interpret=interpret, **square_bkw(p),
    )
    o_suf = flash_attention_rect(
        q[:, p:], k, v, causal=True, q_offset=p, scale=scale,
        interpret=interpret, **bkw,
    )
    return jnp.concatenate([o_pre, o_suf], axis=1)


def prefix_lm_attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    prefix_len: int,
    scale: Optional[float] = None,
) -> jax.Array:
    """Dense O(T^2) reference (and non-flash fallback): the same
    mask materialized, softmax in f32."""
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d**0.5)
    pos = jnp.arange(t)
    mask = (pos[None, :] <= pos[:, None]) | (pos[None, :] < prefix_len)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", w, v.astype(jnp.float32)
    ).astype(q.dtype)
