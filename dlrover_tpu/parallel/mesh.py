"""Named-axis device mesh fabric.

The TPU-native replacement for the reference's named process-group
fabric (atorch/distributed/distributed.py:320 ``create_parallel_group``
building strided NCCL groups per name): here one
``jax.sharding.Mesh`` with named axes is the single source of truth for
DP/FSDP/PP/TP/SP/EP topology, and XLA compiles the collectives onto
ICI/DCN — no wrapper modules, no group bookkeeping.

Axis order encodes the physical hierarchy: the innermost axes change
fastest across physically-adjacent chips, so put bandwidth-hungry axes
(``tensor``) innermost (ICI neighbors) and gradient-sync axes
(``data``) outermost where they may ride DCN across slices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from dlrover_tpu.common.log import get_logger

logger = get_logger("mesh")

# Canonical axis order, outermost (DCN-friendly) to innermost (ICI).
AXIS_ORDER: Tuple[str, ...] = (
    "data",
    "fsdp",
    "pipe",
    "seq",
    "expert",
    "tensor",
)


@dataclasses.dataclass
class MeshConfig:
    """Sizes of every parallel axis. ``-1`` on one axis = absorb all
    remaining devices (like torchrun's nnodes inference)."""

    data: int = 1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1
    tensor: int = 1
    # Number of TPU slices the job spans; >1 splits the outermost axis
    # over DCN (multi-slice training).
    num_slices: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshConfig":
        """Fill a single -1 axis so the product equals n_devices."""
        sizes = self.axis_sizes()
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}"
            )
        return MeshConfig(**sizes, num_slices=self.num_slices)

    @property
    def total(self) -> int:
        return math.prod(self.axis_sizes().values())


def group_devices_by_slice(
    devices: Sequence[jax.Device],
    num_slices: int,
    slice_ids: Optional[Sequence[int]] = None,
) -> Tuple[List[jax.Device], List[int]]:
    """Order devices so slice members are contiguous blocks.

    ``slice_ids`` overrides per-device slice assignment (virtual
    slices on CPU tests); otherwise the TPU runtime's
    ``device.slice_index`` is used. When neither distinguishes slices
    (single-slice hardware faked into num_slices), the list is split
    into equal contiguous blocks. Returns (ordered_devices,
    slice_id_per_ordered_device).
    """
    n = len(devices)
    if n % num_slices:
        raise ValueError(
            f"{n} devices not divisible into {num_slices} slices"
        )
    per_slice = n // num_slices
    if slice_ids is None:
        slice_ids = [
            getattr(d, "slice_index", 0) or 0 for d in devices
        ]
    distinct = sorted(set(slice_ids))
    if len(distinct) == num_slices:
        groups: Dict[int, List[jax.Device]] = {s: [] for s in distinct}
        for d, s in zip(devices, slice_ids):
            groups[s].append(d)
        bad = {
            s: len(g) for s, g in groups.items() if len(g) != per_slice
        }
        if bad:
            raise ValueError(
                f"uneven slices (want {per_slice}/slice): {bad}"
            )
        ordered: List[jax.Device] = []
        ordered_ids: List[int] = []
        for s in distinct:
            ordered.extend(groups[s])
            ordered_ids.extend([s] * per_slice)
        return ordered, ordered_ids
    if len(distinct) == 1:
        # no slice info: contiguous equal split (virtual slices)
        ids = [i // per_slice for i in range(n)]
        return list(devices), ids
    raise ValueError(
        f"devices span {len(distinct)} slices but num_slices="
        f"{num_slices}"
    )


def build_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
    slice_ids: Optional[Sequence[int]] = None,
) -> Mesh:
    """Build the job mesh.

    Single-slice: devices are reshaped in canonical axis order. The
    device list from ``jax.devices()`` enumerates ICI-adjacent chips
    contiguously, so innermost mesh axes land on ICI neighbors.

    Multi-slice (num_slices > 1): devices are grouped so each slice is
    one contiguous block of the outermost non-trivial axis (which must
    be divisible by num_slices) — only that axis's collectives cross
    DCN, everything inner stays on ICI. Slice membership comes from
    the TPU runtime (``device.slice_index``) or an explicit
    ``slice_ids`` list (virtual slices in CPU tests). This is the
    capability the reference reaches via per-group NCCL bootstrap
    across nodes (atorch/distributed/distributed.py:587).
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config.resolve(len(devices))
    sizes = config.axis_sizes()
    if config.num_slices > 1:
        outer = next(
            (a for a in AXIS_ORDER if sizes[a] > 1), AXIS_ORDER[0]
        )
        if sizes[outer] % config.num_slices:
            raise ValueError(
                f"outermost axis {outer}={sizes[outer]} not divisible "
                f"by num_slices={config.num_slices}"
            )
        devices, _ = group_devices_by_slice(
            devices, config.num_slices, slice_ids
        )
        per_slice = len(devices) // config.num_slices
        logger.info(
            "multi-slice mesh: %d slices x %d devices; axis %r "
            "crosses DCN",
            config.num_slices,
            per_slice,
            outer,
        )
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    logger.info(
        "mesh: %s over %d devices",
        {a: s for a, s in sizes.items() if s > 1} or {"data": 1},
        len(devices),
    )
    return mesh


def mesh_slice_blocks(mesh: Mesh, num_slices: int) -> List[List]:
    """The per-slice device blocks of a multi-slice mesh (flat device
    order), for asserting slice purity and for slice-aware ops."""
    flat = list(mesh.devices.flat)
    per_slice = len(flat) // num_slices
    return [
        flat[i * per_slice:(i + 1) * per_slice]
        for i in range(num_slices)
    ]


def single_device_mesh() -> Mesh:
    """A trivial mesh over one device (bench / single-chip paths)."""
    return build_mesh(MeshConfig(), devices=jax.devices()[:1])
