"""Sequence-parallel attention dispatcher: ring vs all-to-all.

The repo carries two context-parallel families (SURVEY §5 long-context
stance; ref: atorch's DistributedSoftmaxAttn/_attn variants,
atorch/modules/distributed_transformer/distributed_attention.py:80):

* ``ring`` (parallel/ring_attention.py): K/V blocks rotate around the
  ``seq`` axis; O(T/s) activation memory, works for any head count,
  but causal work is imbalanced by ring position.
* ``a2a`` (parallel/ulysses.py): one all_to_all turns sequence shards
  into head shards, every device runs full-sequence flash attention
  over its head group; perfectly balanced causal work, but needs
  heads (per tensor shard) divisible by the seq axis and holds full-T
  activations during attention.

``make_seq_attention`` is the one constructor models and the strategy
engine use: an explicit ``seq_impl`` forces a family, ``"auto"``
applies :func:`choose_seq_impl` at trace time (head count is static
under jit, so the choice compiles away).
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

SEQ_IMPLS = ("auto", "ring", "a2a")


def choose_seq_impl(
    n_heads: int, seq_shards: int, tensor_shards: int = 1
) -> str:
    """The auto rule: a2a when every seq shard can own an equal slice
    of this tensor shard's heads (better causal load balance, one
    bulk exchange instead of s-1 hops), ring otherwise (no head-count
    constraint, O(T/s) memory)."""
    if seq_shards <= 1:
        return "ring"  # degenerate: ring's single-shard fallback
    if n_heads % tensor_shards:
        return "ring"
    heads_per_shard = n_heads // tensor_shards
    return "a2a" if heads_per_shard % seq_shards == 0 else "ring"


def make_seq_attention(
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    impl: str = "auto",
    seq_impl: str = "auto",
    window: Optional[int] = None,
):
    """Sharded attention for a mesh with a ``seq`` axis.

    ``impl`` picks the kernel (flash/xla/auto, as in
    ring_attention.make_sharded_attention); ``seq_impl`` picks the
    parallelism family (ring/a2a/auto). ``window`` applies the
    sliding-window band on whichever family is chosen (ring: static
    band-dead hop skipping; a2a: banded inner kernel). The returned
    fn takes global [B, T, H, D] q/k/v under jit.
    """
    if seq_impl not in SEQ_IMPLS:
        raise ValueError(
            f"unknown seq_impl {seq_impl!r}; expected one of {SEQ_IMPLS}"
        )
    from dlrover_tpu.parallel.ring_attention import make_sharded_attention
    from dlrover_tpu.parallel.ulysses import make_a2a_attention

    kwargs = dict(
        causal=causal,
        axis_name=axis_name,
        batch_axes=batch_axes,
        head_axis=head_axis,
        impl=impl,
        window=window,
    )
    if seq_impl == "ring":
        return make_sharded_attention(mesh, **kwargs)
    if seq_impl == "a2a":
        return make_a2a_attention(mesh, **kwargs)

    seq_shards = mesh.shape.get(axis_name, 1)
    tensor_shards = (
        mesh.shape.get(head_axis, 1) if head_axis is not None else 1
    )
    built = {}

    def attn(q, k, v):
        # q.shape[2] is the GLOBAL head count (shard_map happens
        # inside the family constructors), static at trace time.
        choice = choose_seq_impl(q.shape[2], seq_shards, tensor_shards)
        if choice not in built:
            ctor = (
                make_a2a_attention
                if choice == "a2a"
                else make_sharded_attention
            )
            built[choice] = ctor(mesh, **kwargs)
        return built[choice](q, k, v)

    # Both families accept compact grouped-query K/V (see
    # ring_attention._gqa_expander / ulysses.a2a_attention).
    attn.supports_gqa = True
    return attn
