"""shard_map across jax versions.

Newer jax exports :func:`jax.shard_map` taking ``check_vma=``; on
older toolchains (this container's 0.4.x jaxlib) the same transform
lives at ``jax.experimental.shard_map.shard_map`` and the kwarg is
spelled ``check_rep=``. Every shard_map user in this repo imports
from here so the whole parallel/ stack (and the suites that exercise
it) works on both — an ImportError at module scope was taking entire
test modules down with it on the older toolchain.
"""

from __future__ import annotations

try:  # jax >= 0.6: public API, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KWARG = "check_vma"
except ImportError:  # older jax: experimental API, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KWARG = "check_rep"


def shard_map(
    f=None, *, mesh, in_specs, out_specs, check_vma: bool = True
):
    """:func:`jax.shard_map` with the repo's calling convention
    (keyword mesh/specs, ``check_vma=``), translated to whatever this
    jax spells it."""
    kwargs = {
        "mesh": mesh,
        "in_specs": in_specs,
        "out_specs": out_specs,
        _CHECK_KWARG: check_vma,
    }
    if f is None:
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)


def use_mesh(mesh):
    """Enter ``mesh`` as the ambient mesh: ``jax.set_mesh`` where it
    exists (jax >= 0.5), else the :class:`~jax.sharding.Mesh` context
    manager the 0.4.x toolchain provides."""
    import jax

    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh
