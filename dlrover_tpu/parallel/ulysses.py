"""All-to-all (Ulysses-style) sequence parallelism.

The second context-parallel family next to parallel/ring_attention.py
(ref: atorch's sequence-parallel integrations; DeepSpeed-Ulysses is
the public construction, PAPERS.md): instead of rotating K/V blocks
around a ring, one ``all_to_all`` swaps the sharded dimension —
sequence-sharded activations [B, T/s, H, D] become head-sharded
full-sequence activations [B, T, H/s, D], every device runs ordinary
(flash) attention over its head group, and the inverse all_to_all
restores sequence sharding.

Trade-offs vs the ring (why both exist):

* two all_to_alls move 3x and 1x the activation bytes once, instead
  of (s-1) K/V block hops — fewer, larger transfers that XLA overlaps
  poorly but ICI switches handle well;
* causal work is perfectly load-balanced (every device sees the full
  sequence), where the causal ring is inherently imbalanced by ring
  position;
* requires heads % seq_shards == 0 and holds full-T activations per
  device for the attention itself — the ring keeps O(T/s) memory and
  scales past head count.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from dlrover_tpu.parallel.shard_map_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def a2a_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
    attn_fn=None,
) -> jax.Array:
    """Attention over ``axis_name``-sharded sequences via head/seq
    all-to-all. Per-device shapes [batch, seq_local, heads, head_dim];
    must run inside shard_map with ``axis_name`` unmapped. ``attn_fn``
    computes full-sequence attention on [B, T, H/s, D] (defaults to
    the models' plain causal attention; pass the flash kernel on TPU).
    """
    n = jax.lax.psum(1, axis_name)
    b, lt, h, d = q.shape
    h_kv = k.shape[2]
    if h % n != 0:
        raise ValueError(
            f"a2a sequence parallelism needs heads ({h}) divisible "
            f"by the '{axis_name}' axis size ({n}); use ring "
            "attention when sequence shards outnumber heads"
        )
    if h_kv != h and h % h_kv:
        raise ValueError(
            f"grouped-query attention needs q heads ({h}) divisible "
            f"by kv heads ({h_kv})"
        )
    if attn_fn is None:
        from dlrover_tpu.models.gpt import _default_attention

        attn_fn = functools.partial(_default_attention, causal=causal)

    # [B, T/s, H, D] -> [B, T, H/s, D]: split the head dim n ways,
    # exchange so each device concatenates every peer's sequence
    # block (axis-index order = global sequence order).
    def swap_to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    g = h // h_kv
    if g > 1 and h_kv % n:
        # Compact kv heads don't split n ways: broadcast BEFORE the
        # exchange (correct, no traffic saving).
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
        g = 1
    qh = swap_to_heads(q)
    kh = swap_to_heads(k)
    vh = swap_to_heads(v)
    if g > 1:
        # Compact grouped-query K/V crossed the a2a at 1/g the bytes;
        # broadcast over the query groups only now, locally.
        kh = jnp.repeat(kh, g, axis=2)
        vh = jnp.repeat(vh, g, axis=2)
    out = attn_fn(qh, kh, vh)
    # [B, T, H/s, D] -> [B, T/s, H, D]
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    ).astype(q.dtype)


def make_a2a_attention(
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    impl: str = "auto",
    window: Optional[int] = None,
):
    """shard_map wrapper mirroring ring_attention.make_sharded_attention
    — drop-in for a model's ``attn_fn`` on a mesh with a ``seq`` axis.

    ``impl``: "flash" runs the Pallas kernel on the full-sequence head
    group, "xla" the einsum path, "auto" picks flash on TPU. Composes
    with tensor parallelism the same way the ring does (heads shard
    over ``tensor`` first; the a2a then needs heads_per_tensor_shard %
    seq_shards == 0).

    ``window`` (requires ``causal=True``): after the all_to_all every
    device holds the FULL sequence for its head group, so the band is
    just the inner kernel's ``window`` — the flash kernel skips
    band-dead kv blocks (O(T*window) per device), the plain path
    masks. Communication is unchanged (the a2a moves activations, not
    K/V blocks, so unlike the ring there is no band-dead traffic to
    skip).
    """
    if impl not in ("auto", "flash", "xla"):
        raise ValueError(f"unknown a2a attention impl {impl!r}")
    if window is not None and not causal:
        raise ValueError(
            "window (sliding-window attention) requires causal=True"
        )
    use_flash = (
        impl == "flash"
        or (impl == "auto" and jax.default_backend() == "tpu")
    )
    if mesh.shape.get(axis_name, 1) == 1:
        from dlrover_tpu.parallel.ring_attention import (
            make_sharded_attention,
        )

        # No sequence sharding: identical to the ring's degenerate
        # case — reuse its plain/flash single-device paths.
        return make_sharded_attention(
            mesh, causal=causal, axis_name=axis_name,
            batch_axes=batch_axes, head_axis=head_axis, impl=impl,
            window=window,
        )

    if use_flash:
        from dlrover_tpu.ops.flash_attention import flash_attention

        inner = functools.partial(
            flash_attention, causal=causal, window=window
        )
    elif window is not None:
        from dlrover_tpu.models.gpt import _default_attention

        inner = functools.partial(
            _default_attention, causal=causal, window=window
        )
    else:
        inner = None  # a2a_attention's default plain path

    spec = P(batch_axes, axis_name, head_axis, None)
    fn = functools.partial(
        a2a_attention,
        axis_name=axis_name,
        causal=causal,
        attn_fn=inner,
    )
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )

    tp = mesh.shape.get(head_axis, 1) if head_axis is not None else 1

    def attn(q, k, v):
        # Same tensor-axis constraint as the ring wrapper: compact
        # K/V must split its head dim over `tensor`, else
        # pre-broadcast (correct, no traffic saving). _gqa_expander
        # also validates the head ratio on the GLOBAL counts.
        if k.shape[2] != q.shape[2] and k.shape[2] % tp:
            from dlrover_tpu.parallel.ring_attention import (
                _gqa_expander,
            )

            expand = _gqa_expander(q.shape[2], k.shape[2])
            k, v = expand(k), expand(v)
        return sharded(q, k, v)

    # Compact grouped-query K/V accepted: it crosses the a2a at
    # 1/q_per_kv the bytes when kv heads split over the axis, and is
    # broadcast locally otherwise (a2a_attention).
    attn.supports_gqa = True
    return attn
