"""Pipeline parallelism over the ``pipe`` mesh axis.

Replaces the reference's PiPPy-based pipeline stack
(atorch/compilers/pipe_compiler/distributed_pippy_compiler.py,
PipelineStage.py:989LoC — FX-traced stage split, torch RPC mailboxes,
1F1B interleaving) with the TPU-idiomatic formulation: a GPipe
schedule written as a ``lax.scan`` inside ``shard_map``, stage hops as
``lax.ppermute`` over ICI neighbors. The schedule is differentiable —
``jax.grad`` through the scan yields the reversed pipeline (backward
microbatch schedule) without any hand-written 1F1B machinery, and
``jax.checkpoint`` on the stage body bounds activation memory the way
1F1B's eager backward does.

Layout contract: stage parameters are stacked on a leading axis of
size n_stages, logically named ``stage`` (sharding.py maps it to the
``pipe`` mesh axis), so each device holds exactly its stage's weights.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from dlrover_tpu.parallel.shard_map_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_body(
    stage_fn: Callable,
    params,  # per-device stage params (leading stage dim of size 1)
    microbatches,  # [M, mb, ...] (replicated across pipe)
    axis_name: str,
    remat: bool,
):
    """Runs inside shard_map. Returns [M, mb, ...] outputs (valid on
    every device after the final psum broadcast)."""
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    total_steps = M + n_stages - 1

    local_params = jax.tree.map(lambda p: p[0], params)
    fn = stage_fn
    if remat:
        fn = jax.checkpoint(stage_fn)

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def step(carry, t):
        outputs, prev_out = carry
        # What flows into this stage at step t: stage 0 injects
        # microbatch t (zeros in the drain phase); others receive the
        # previous step's output from their left neighbor.
        recv = jax.lax.ppermute(prev_out, axis_name, fwd_perm)
        mb_idx = jnp.clip(t, 0, M - 1)
        injected = jax.lax.dynamic_index_in_dim(
            microbatches, mb_idx, axis=0, keepdims=False
        )
        x_in = jnp.where(stage == 0, injected, recv)
        y = fn(local_params, x_in)
        # Last stage finished microbatch t - (n_stages - 1) at step t.
        out_idx = t - (n_stages - 1)
        write = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        contribution = jnp.where(write, 1.0, 0.0).astype(y.dtype) * y
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jax.lax.dynamic_index_in_dim(
                outputs, jnp.clip(out_idx, 0, M - 1), 0, keepdims=False
            )
            + contribution,
            jnp.clip(out_idx, 0, M - 1),
            0,
        )
        return (outputs, y), None

    y_shape = jax.eval_shape(fn, local_params, microbatches[0])
    outputs0 = jnp.zeros((M,) + y_shape.shape, y_shape.dtype)
    prev0 = jnp.zeros(y_shape.shape, y_shape.dtype)
    (outputs, _), _ = jax.lax.scan(
        step, (outputs0, prev0), jnp.arange(total_steps)
    )
    # Only the last stage holds real outputs; broadcast them to every
    # stage so the loss is computable anywhere (GSPMD psum over pipe).
    return jax.lax.psum(
        jnp.where(stage == n_stages - 1, 1.0, 0.0).astype(outputs.dtype)
        * outputs,
        axis_name,
    )


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable,
    axis_name: str = "pipe",
    remat: bool = True,
    params_spec: Optional[Any] = None,
    batch_spec: P = P(),
):
    """Builds ``apply(stage_params, microbatches) -> outputs``.

    stage_fn(stage_local_params, x[mb, ...]) -> y[mb, ...] applies ONE
    stage. ``stage_params`` leaves are stacked [n_stages, ...] and get
    sharded over ``axis_name``; microbatches [M, mb, ...] are
    replicated over ``axis_name`` (shard batch dims over data/fsdp
    axes via ``batch_spec``).
    """
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        def apply_single(stage_params, microbatches):
            local = jax.tree.map(lambda p: p[0], stage_params)
            fn = jax.checkpoint(stage_fn) if remat else stage_fn
            return jax.lax.map(lambda mb: fn(local, mb), microbatches)

        return apply_single

    if params_spec is None:
        params_spec = P(axis_name)
    body = functools.partial(
        _pipeline_body,
        stage_fn,
        axis_name=axis_name,
        remat=remat,
    )
    mb_spec = P(None, *batch_spec)  # leading microbatch dim replicated
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )


# ---------------------------------------------------------------------------
# 1F1B / interleaved schedule
# ---------------------------------------------------------------------------
#
# Capability parity with the reference's 1F1B + interleaved pipeline
# (atorch PipelineStage.py:1-989, StageInterleaver.py), built the TPU
# way: a lockstep wave schedule inside shard_map where every wave does
# one forward chunk and one backward chunk per device, activations hop
# stages through a single circular ``ppermute``, and gradients are
# computed manually with per-chunk ``jax.vjp`` against a bounded
# ring-buffer stash of chunk inputs. JAX never differentiates the scan,
# so the stash — O(n_stages * v_chunks) microbatch activations — is the
# ONLY schedule memory; GPipe-via-grad stashes O(M) scan residuals.
#
# Schedule (devices d = 0..n-1, virtual chunks v = 0..V-1, logical
# stage l = v*n + d, microbatches processed in groups of n):
#   forward  of mb (g*n + r) at chunk (d, v) on wave  t = g*nV + v*n + r + d
#   backward of the same     at wave  t = (nV-1) + g*nV + (V-1-v)*n + r + (n-1-d)
# Both decompose uniquely per (device, wave) — one F and one B chunk
# per device per wave, outputs consumed exactly one wave later by the
# circular neighbor (forward d -> d+1 mod n, backward d -> d-1 mod n,
# the mod-n wrap carrying chunk v outputs into chunk v+1 inputs).
# V=1 is plain (non-interleaved) 1F1B; V>1 shrinks the pipeline bubble
# from ~2(n-1) stage-times toward ~n(1 + 1/V).


def _chunk_at(params, v, V):
    """Dynamic-index chunk ``v`` out of [V, ...]-stacked local leaves."""
    return jax.tree.map(
        lambda p: jax.lax.dynamic_index_in_dim(
            p, jnp.clip(v, 0, V - 1), 0, keepdims=False
        ),
        params,
    )


def _1f1b_body(
    stage_fn: Callable,
    loss_fn: Callable,
    params,        # local [1, V, ...] leaves
    microbatches,  # [M, mb, ...] replicated over pipe
    targets,       # [M, ...] replicated over pipe
    head_params,   # extra loss-side params (None = plain loss_fn)
    axis_name: str,
    V: int,
    n: int,
    batch_axes: tuple = (),
    collect_input_grads: bool = False,
    stage_aux: bool = False,
):
    d = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    if M % n:
        raise ValueError(
            f"microbatch count {M} must be a multiple of the "
            f"{axis_name} axis size {n}"
        )
    for p in jax.tree.leaves(params):
        if p.shape[1] != V:
            raise ValueError(
                f"stage params chunk dim {p.shape[1]} != v_chunks "
                f"{V}: stack with split_stages_interleaved(tree, "
                f"{n}, {V})"
            )
    nV = n * V
    G = M // n
    C = nV - 1  # backward wave offset
    total_waves = C + (G - 1) * nV + (V - 1) * n + 2 * (n - 1) + 1

    local_params = jax.tree.map(lambda p: p[0], params)  # [V, ...]
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]

    y_shape = jax.eval_shape(
        stage_fn, _chunk_at(local_params, jnp.int32(0), V),
        microbatches[0],
    )
    if stage_aux:
        y_shape = y_shape[0]
    # Ring buffer of stashed chunk inputs, per chunk. The in-flight
    # window per chunk is <= ~2n + n sawtooth slack; 4n+4 is safe and
    # still O(n), independent of M (the whole point vs GPipe).
    R = min(M, 4 * n + 4)

    def wave(carry, t):
        (y_prev, d_prev, stash, grad_acc, loss_acc,
         head_acc, dx_buf, aux_acc) = carry

        # ---- forward sub-step -----------------------------------------
        recv = jax.lax.ppermute(y_prev, axis_name, fwd_perm)
        u = t - d
        g_f = u // nV
        rem = u % nV
        v_f = rem // n
        r_f = rem % n
        mb_f = g_f * n + r_f
        valid_f = jnp.logical_and(u >= 0, mb_f < M)
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(mb_f, 0, M - 1), 0, keepdims=False
        )
        is_first = jnp.logical_and(d == 0, v_f == 0)
        x_in = jnp.where(is_first, inject, recv)
        if stage_aux:
            y, aux_f = stage_fn(_chunk_at(local_params, v_f, V), x_in)
            # where, not multiply: bubble waves compute aux on
            # garbage inputs and 0 * inf would poison the sum
            aux_acc = aux_acc + jnp.where(
                valid_f, aux_f.astype(jnp.float32), 0.0
            )
        else:
            y = stage_fn(_chunk_at(local_params, v_f, V), x_in)

        slot_f = jnp.clip(v_f, 0, V - 1) * R + mb_f % R
        old = jax.lax.dynamic_index_in_dim(
            stash, slot_f, 0, keepdims=False
        )
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(valid_f, x_in, old), slot_f, 0
        )

        # ---- backward sub-step ----------------------------------------
        recv_d = jax.lax.ppermute(d_prev, axis_name, bwd_perm)
        ub = t - C - (n - 1 - d)
        g_b = ub // nV
        remb = ub % nV
        v_b = (V - 1) - remb // n
        r_b = remb % n
        mb_b = g_b * n + r_b
        valid_b = jnp.logical_and(ub >= 0, mb_b < M)
        slot_b = jnp.clip(v_b, 0, V - 1) * R + mb_b % R
        x_b = jax.lax.dynamic_index_in_dim(
            stash, slot_b, 0, keepdims=False
        )
        chunk_p = _chunk_at(local_params, v_b, V)
        if stage_aux:
            (y_b, _aux_b), vjp_fn = jax.vjp(stage_fn, chunk_p, x_b)
        else:
            y_b, vjp_fn = jax.vjp(stage_fn, chunk_p, x_b)
        tgt = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(mb_b, 0, M - 1), 0, keepdims=False
            ),
            targets,
        )
        is_last = jnp.logical_and(d == n - 1, v_b == V - 1)
        if head_params is None:
            loss_mb, dy_loss = jax.value_and_grad(
                lambda yy: loss_fn(yy, tgt)
            )(y_b)
            dhead = None
        else:
            # The head (norm + unembedding CE for a transformer) can
            # dwarf a single stage's FLOPs; lax.cond skips its
            # forward+backward entirely on non-last stages instead of
            # masking the result to zero afterwards.
            def _head_branch(args):
                yy, hp = args
                return jax.value_and_grad(
                    lambda y_, h_: loss_fn(y_, tgt, h_),
                    argnums=(0, 1),
                )(yy, hp)

            def _skip_branch(args):
                yy, hp = args
                return (
                    jnp.float32(0.0),
                    (
                        jnp.zeros_like(yy),
                        jax.tree.map(jnp.zeros_like, hp),
                    ),
                )

            loss_mb, (dy_loss, dhead) = jax.lax.cond(
                is_last, _head_branch, _skip_branch,
                (y_b, head_params),
            )
        dy = jnp.where(is_last, dy_loss, recv_d)
        if stage_aux:
            # aux cotangent 1 per VALID backward (un-meaned, same /M
            # as the grads below): d(total aux)/d(this chunk's aux)
            daux = jnp.where(valid_b, 1.0, 0.0).astype(jnp.float32)
            dp, dx = vjp_fn((dy, daux))
        else:
            dp, dx = vjp_fn(dy)
        # jnp.where, NOT multiply-by-mask: bubble waves run stage_fn
        # on garbage stash values, and 0 * inf = NaN would poison the
        # accumulator for the rest of the scan.
        grad_acc = jax.tree.map(
            lambda acc, g: jax.lax.dynamic_update_index_in_dim(
                acc,
                jax.lax.dynamic_index_in_dim(
                    acc, jnp.clip(v_b, 0, V - 1), 0, keepdims=False
                )
                + jnp.where(valid_b, g.astype(acc.dtype), 0.0),
                jnp.clip(v_b, 0, V - 1),
                0,
            ),
            grad_acc,
            dp,
        )
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(valid_b, is_last), loss_mb, 0.0
        )
        if head_acc is not None:
            take_head = jnp.logical_and(valid_b, is_last)
            head_acc = jax.tree.map(
                lambda acc, g: acc
                + jnp.where(take_head, g.astype(acc.dtype), 0.0),
                head_acc,
                dhead,
            )
        if dx_buf is not None:
            # Stage-0 chunk-0 backwards produce d(loss)/d(microbatch):
            # the caller differentiates its pre-pipeline compute
            # (e.g. the embedding) with these cotangents.
            is_first_b = jnp.logical_and(d == 0, v_b == 0)
            take_dx = jnp.logical_and(valid_b, is_first_b)
            slot = jnp.clip(mb_b, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(
                dx_buf, slot, 0, keepdims=False
            )
            dx_buf = jax.lax.dynamic_update_index_in_dim(
                dx_buf,
                jnp.where(take_dx, dx.astype(dx_buf.dtype), cur),
                slot,
                0,
            )
        d_prev_new = jnp.where(valid_b, dx, jnp.zeros_like(dx))
        return (
            y, d_prev_new, stash, grad_acc, loss_acc, head_acc,
            dx_buf, aux_acc,
        ), None

    y0 = jnp.zeros(y_shape.shape, y_shape.dtype)
    d0 = jnp.zeros(y_shape.shape, y_shape.dtype)
    stash0 = jnp.zeros((V * R,) + microbatches.shape[1:],
                       microbatches.dtype)
    grad0 = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), local_params
    )
    head0 = (
        None
        if head_params is None
        else jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), head_params
        )
    )
    dx0 = (
        jnp.zeros((M,) + y_shape.shape, jnp.float32)
        if collect_input_grads
        else None
    )
    (
        y_f, d_f, _, grads, loss, head_grads, dx_all, aux_sum
    ), _ = jax.lax.scan(
        wave,
        (
            y0, d0, stash0, grad0, jnp.float32(0.0), head0, dx0,
            jnp.float32(0.0),
        ),
        jnp.arange(total_waves),
    )
    # Mean over microbatches; loss lives on the last logical stage
    # only, grads on their own stage — psum the loss, keep grads local.
    loss = jax.lax.psum(loss, axis_name) / M
    if stage_aux:
        # every device accumulated its own chunks' aux; the total is
        # the cross-pipe sum, meaned over microbatches like the loss
        loss = loss + jax.lax.psum(aux_sum, axis_name) / M
    grads = jax.tree.map(lambda g: g / M, grads)
    if head_grads is not None:
        # Nonzero only on the last logical stage's device: replicate.
        head_grads = jax.tree.map(
            lambda g: jax.lax.psum(g, axis_name) / M, head_grads
        )
    if dx_all is not None:
        # Nonzero only on stage-0 devices: replicate across pipe.
        # Per-microbatch cotangents are NOT divided by M — the caller
        # applies the same 1/M mean when reducing its pre-pipeline
        # grads, keeping d(mean loss)/d(input) exact.
        dx_all = jax.lax.psum(dx_all, axis_name)
        if batch_axes:
            # loss_fn normalizes over the SHARD-LOCAL microbatch rows;
            # the global loss is the pmean over batch shards, so each
            # shard's input cotangent carries a 1/nshards factor (the
            # stage grads get this via their pmean below — dx stays
            # shard-local, so scale it directly).
            nshards = jax.lax.psum(1, batch_axes)
            dx_all = dx_all / nshards
    if batch_axes:
        # microbatches are sharded over these axes: each shard saw
        # only its slice, so loss/grads are shard-local means.
        loss = jax.lax.pmean(loss, batch_axes)
        grads = jax.tree.map(
            lambda g: jax.lax.pmean(g, batch_axes), grads
        )
        if head_grads is not None:
            head_grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, batch_axes), head_grads
            )
        # dx_all stays shard-local: it is the cotangent of THIS
        # shard's microbatch slice.
    out_grads = jax.tree.map(lambda g: g[None], grads)  # [1, V, ...]
    if head_params is None and not collect_input_grads:
        return loss, out_grads
    return loss, out_grads, head_grads, dx_all


def pipeline_train(
    mesh: Mesh,
    stage_fn: Callable,
    loss_fn: Callable,
    axis_name: str = "pipe",
    v_chunks: int = 1,
    params_spec: Optional[Any] = None,
    batch_spec: P = P(),
    with_head: bool = False,
    collect_input_grads: bool = False,
    stage_aux: bool = False,
):
    """Builds a 1F1B (``v_chunks=1``) or interleaved-1F1B training
    step: ``step(stage_params, microbatches, targets) -> (loss,
    grads)``.

    * ``stage_params`` leaves are stacked ``[n_stages, v_chunks, ...]``
      (see :func:`split_stages_interleaved`); chunk ``(d, v)`` is
      logical pipeline stage ``v * n_stages + d``.
    * ``stage_fn(chunk_params, x[mb, ...]) -> y[mb, ...]`` applies one
      chunk; all chunk inputs/outputs share one activation shape.
    * ``loss_fn(y[mb, ...], target) -> scalar`` is evaluated per
      microbatch at the last logical stage; the returned ``loss`` and
      ``grads`` are means over all ``M`` microbatches.
    * ``M`` must be a multiple of the ``pipe`` axis size.

    Full-model hooks (how a transformer with an embedding and an
    unembedding head pipelines its uniform-activation middle):

    * ``with_head=True``: the step takes a fourth argument —
      replicated loss-side params — and ``loss_fn(y, target,
      head_params)``; the step returns their mean gradient (psum'd
      from the last logical stage) as a third output.
    * ``collect_input_grads=True``: the step also returns
      d(mean loss)/d(microbatches) * M, the per-microbatch cotangents
      flowing out of logical stage 0 — the caller backpropagates its
      pre-pipeline compute (embedding) with them and applies the same
      1/M mean itself.
    * ``stage_aux=True``: ``stage_fn`` returns ``(y, aux)`` with a
      scalar auxiliary loss per chunk (MoE router load-balancing);
      the step's loss adds the cross-stage, microbatch-meaned aux sum
      and differentiates through it (cotangent 1 per valid backward).

    Unlike :func:`pipeline_apply` + ``jax.grad`` (GPipe), activation
    stash is O(n_stages * v_chunks) microbatch inputs instead of O(M)
    scan residuals, and the backward schedule starts while forwards
    are still draining — the 1F1B property (ref PipelineStage.py).
    """
    n_stages = mesh.shape.get(axis_name, 1)
    if params_spec is None:
        params_spec = P(axis_name)
    plain = not with_head and not collect_input_grads

    if n_stages == 1:
        def step_single(stage_params, microbatches, targets,
                        head_params=None):
            local = jax.tree.map(lambda p: p[0], stage_params)

            def whole(params_, mbs, hp):
                def one(mb, tgt):
                    x = mb
                    aux_total = jnp.float32(0.0)
                    for v in range(v_chunks):
                        chunk = jax.tree.map(
                            lambda p: p[v], params_
                        )
                        if stage_aux:
                            x, aux = stage_fn(chunk, x)
                            aux_total = aux_total + aux
                        else:
                            x = stage_fn(chunk, x)
                    base = (
                        loss_fn(x, tgt, hp)
                        if with_head
                        else loss_fn(x, tgt)
                    )
                    return base + aux_total

                losses = jax.vmap(one)(mbs, targets)
                return jnp.mean(losses)

            argnums = (0,)
            if collect_input_grads:
                argnums += (1,)
            if with_head:
                argnums += (2,)
            loss, grad_parts = jax.value_and_grad(
                whole, argnums=argnums
            )(local, microbatches, head_params)
            parts = dict(zip(argnums, grad_parts))
            out = (loss, jax.tree.map(lambda g: g[None], parts[0]))
            if plain:
                return out
            M = microbatches.shape[0]
            return out + (
                parts.get(2) if with_head else None,
                # match the sharded path's un-meaned convention
                jax.tree.map(lambda g: g * M, parts[1])
                if collect_input_grads
                else None,
            )

        return step_single

    batch_axes: list = []
    for e in batch_spec:
        if e is None:
            continue
        batch_axes.extend(e if isinstance(e, tuple) else (e,))
    body = functools.partial(
        _1f1b_body,
        stage_fn,
        loss_fn,
        axis_name=axis_name,
        V=v_chunks,
        n=n_stages,
        batch_axes=tuple(batch_axes),
        collect_input_grads=collect_input_grads,
        stage_aux=stage_aux,
    )
    mb_spec = P(None, *batch_spec)
    if plain:
        def body_plain(params, microbatches, targets):
            return body(params, microbatches, targets, None)

        return shard_map(
            body_plain,
            mesh=mesh,
            in_specs=(params_spec, mb_spec, mb_spec),
            out_specs=(P(), P(axis_name)),
            check_vma=False,
        )

    def body_full(params, microbatches, targets, head_params):
        return body(params, microbatches, targets, head_params)

    sharded = shard_map(
        body_full,
        mesh=mesh,
        in_specs=(params_spec, mb_spec, mb_spec, P()),
        out_specs=(
            P(),
            P(axis_name),
            P() if with_head else None,
            mb_spec if collect_input_grads else None,
        ),
        check_vma=False,
    )

    def step(stage_params, microbatches, targets, head_params=None):
        return sharded(stage_params, microbatches, targets, head_params)

    return step


def split_stages_interleaved(tree, n_stages: int, v_chunks: int):
    """Reshape a scanned-layer tree [L, ...] into
    [n_stages, v_chunks, L/(n_stages*v_chunks), ...] where chunk
    (d, v) holds the layers of LOGICAL stage v*n_stages + d (the
    interleaved round-robin placement, ref StageInterleaver.py)."""
    nV = n_stages * v_chunks

    def reshape(p):
        L = p.shape[0]
        if L % nV:
            raise ValueError(
                f"layer count {L} not divisible by {nV} chunks"
            )
        # [V, n, L/nV, ...] -> transpose to [n, V, ...]: element
        # [d, v] = logical chunk v*n + d.
        q = p.reshape((v_chunks, n_stages, L // nV) + p.shape[1:])
        return jnp.swapaxes(q, 0, 1)

    return jax.tree.map(reshape, tree)


def split_stages(tree, n_stages: int):
    """Reshape a scanned-layer param tree [L, ...] into
    [n_stages, L // n_stages, ...] for pipeline stacking."""

    def reshape(p):
        L = p.shape[0]
        if L % n_stages:
            raise ValueError(
                f"layer count {L} not divisible by {n_stages} stages"
            )
        return p.reshape((n_stages, L // n_stages) + p.shape[1:])

    return jax.tree.map(reshape, tree)
