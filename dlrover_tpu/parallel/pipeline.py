"""Pipeline parallelism over the ``pipe`` mesh axis.

Replaces the reference's PiPPy-based pipeline stack
(atorch/compilers/pipe_compiler/distributed_pippy_compiler.py,
PipelineStage.py:989LoC — FX-traced stage split, torch RPC mailboxes,
1F1B interleaving) with the TPU-idiomatic formulation: a GPipe
schedule written as a ``lax.scan`` inside ``shard_map``, stage hops as
``lax.ppermute`` over ICI neighbors. The schedule is differentiable —
``jax.grad`` through the scan yields the reversed pipeline (backward
microbatch schedule) without any hand-written 1F1B machinery, and
``jax.checkpoint`` on the stage body bounds activation memory the way
1F1B's eager backward does.

Layout contract: stage parameters are stacked on a leading axis of
size n_stages, logically named ``stage`` (sharding.py maps it to the
``pipe`` mesh axis), so each device holds exactly its stage's weights.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_body(
    stage_fn: Callable,
    params,  # per-device stage params (leading stage dim of size 1)
    microbatches,  # [M, mb, ...] (replicated across pipe)
    axis_name: str,
    remat: bool,
):
    """Runs inside shard_map. Returns [M, mb, ...] outputs (valid on
    every device after the final psum broadcast)."""
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    total_steps = M + n_stages - 1

    local_params = jax.tree.map(lambda p: p[0], params)
    fn = stage_fn
    if remat:
        fn = jax.checkpoint(stage_fn)

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def step(carry, t):
        outputs, prev_out = carry
        # What flows into this stage at step t: stage 0 injects
        # microbatch t (zeros in the drain phase); others receive the
        # previous step's output from their left neighbor.
        recv = jax.lax.ppermute(prev_out, axis_name, fwd_perm)
        mb_idx = jnp.clip(t, 0, M - 1)
        injected = jax.lax.dynamic_index_in_dim(
            microbatches, mb_idx, axis=0, keepdims=False
        )
        x_in = jnp.where(stage == 0, injected, recv)
        y = fn(local_params, x_in)
        # Last stage finished microbatch t - (n_stages - 1) at step t.
        out_idx = t - (n_stages - 1)
        write = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        contribution = jnp.where(write, 1.0, 0.0).astype(y.dtype) * y
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jax.lax.dynamic_index_in_dim(
                outputs, jnp.clip(out_idx, 0, M - 1), 0, keepdims=False
            )
            + contribution,
            jnp.clip(out_idx, 0, M - 1),
            0,
        )
        return (outputs, y), None

    y_shape = jax.eval_shape(fn, local_params, microbatches[0])
    outputs0 = jnp.zeros((M,) + y_shape.shape, y_shape.dtype)
    prev0 = jnp.zeros(y_shape.shape, y_shape.dtype)
    (outputs, _), _ = jax.lax.scan(
        step, (outputs0, prev0), jnp.arange(total_steps)
    )
    # Only the last stage holds real outputs; broadcast them to every
    # stage so the loss is computable anywhere (GSPMD psum over pipe).
    return jax.lax.psum(
        jnp.where(stage == n_stages - 1, 1.0, 0.0).astype(outputs.dtype)
        * outputs,
        axis_name,
    )


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable,
    axis_name: str = "pipe",
    remat: bool = True,
    params_spec: Optional[Any] = None,
    batch_spec: P = P(),
):
    """Builds ``apply(stage_params, microbatches) -> outputs``.

    stage_fn(stage_local_params, x[mb, ...]) -> y[mb, ...] applies ONE
    stage. ``stage_params`` leaves are stacked [n_stages, ...] and get
    sharded over ``axis_name``; microbatches [M, mb, ...] are
    replicated over ``axis_name`` (shard batch dims over data/fsdp
    axes via ``batch_spec``).
    """
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        def apply_single(stage_params, microbatches):
            local = jax.tree.map(lambda p: p[0], stage_params)
            fn = jax.checkpoint(stage_fn) if remat else stage_fn
            return jax.lax.map(lambda mb: fn(local, mb), microbatches)

        return apply_single

    if params_spec is None:
        params_spec = P(axis_name)
    body = functools.partial(
        _pipeline_body,
        stage_fn,
        axis_name=axis_name,
        remat=remat,
    )
    mb_spec = P(None, *batch_spec)  # leading microbatch dim replicated
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(params_spec, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )


def split_stages(tree, n_stages: int):
    """Reshape a scanned-layer param tree [L, ...] into
    [n_stages, L // n_stages, ...] for pipeline stacking."""

    def reshape(p):
        L = p.shape[0]
        if L % n_stages:
            raise ValueError(
                f"layer count {L} not divisible by {n_stages} stages"
            )
        return p.reshape((n_stages, L // n_stages) + p.shape[1:])

    return jax.tree.map(reshape, tree)
