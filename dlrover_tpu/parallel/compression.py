"""Compressed gradient synchronization over a mesh axis.

Reference counterparts: the quantized-reduction CUDA kernels
(atorch/ops/csrc/quantization/quant_reduce.cu,
swizzled_quantize.cu) and ADP's gradient-compression DDP hooks
(atorch/data_parallel/adp.py). On TPU the equivalent lever is the
*collective schedule*, not a custom allreduce: an allreduce is a
reduce-scatter (which must stay high-precision — it sums) followed by
an all-gather (which is pure broadcast and compresses safely). This
module implements

    psum_mean = psum_scatter(bf16/f32)  ->  quantize shard
                -> all_gather(int8 + per-block scales) -> dequantize

cutting the all-gather phase to ~1/2 (int8 vs bf16) or ~1/4 (packed
int4) of the bytes — worth it exactly where the data axis crosses DCN
(multi-slice outer axis, parallel/mesh.py), which is also where the
reference deployed gradient compression.

Opt-in via ``make_compressed_train_step`` for the replicated-params
data-parallel regime; per-leaf quantization error is bounded by the
per-block absmax / 127 (or /7 at 4 bits), and tests bound the
end-to-end gradient deviation.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from dlrover_tpu.parallel.shard_map_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

# Deliberately the jnp (_ref) quantizers, NOT the Pallas kernels:
# inside shard_map XLA fuses these elementwise ops straight into the
# collective schedule (quantize overlaps the reduce-scatter epilogue),
# whereas a pallas_call is an opaque boundary XLA cannot fuse or
# overlap through. Wire format (int8 / packed-nibble uint8 + f32
# per-block scales) is identical to the kernel path by construction —
# test_int4_wire_format_is_packed pins that.
from dlrover_tpu.ops.quantization import (
    dequantize_blockwise_4bit_ref,
    dequantize_blockwise_ref,
    quantize_blockwise_4bit_ref,
    quantize_blockwise_ref,
)

# Below this many elements the collective is latency-bound and
# padding to n*block would inflate tiny leaves (biases, norms) by
# orders of magnitude — plain pmean wins.
DEFAULT_MIN_SIZE = 16384


def compressed_psum_mean(
    x: jax.Array,
    axis_name: str,
    bits: int = 8,
    block: int = 1024,
    min_size: int = DEFAULT_MIN_SIZE,
) -> jax.Array:
    """Mean of ``x`` over ``axis_name`` with an int-quantized
    all-gather phase (packed two-per-byte at 4 bits — the
    ops/quantization.py wire format). Must run inside shard_map;
    returns the mean replicated across the axis (like ``lax.pmean``).
    Leaves smaller than ``min_size`` fall back to plain pmean.
    """
    if bits not in (4, 8):
        raise ValueError("bits must be 4 or 8")
    if x.size < min_size:
        return jax.lax.pmean(x, axis_name)
    n = jax.lax.psum(1, axis_name)
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)  # keep input dtype: RS bytes match baseline
    size = flat.size
    pad = (-size) % (n * block)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunk = flat.size // n
    # Phase 1: reduce-scatter in the gradient dtype (sums must not
    # quantize; same precision/bytes as the baseline psum's RS phase).
    shard = jax.lax.psum_scatter(
        flat.reshape(n, chunk), axis_name, scatter_dimension=0,
        tiled=False,
    )  # [chunk], this device's reduced shard
    # Phase 2: quantize the reduced shard, broadcast cheaply.
    shard32 = shard.astype(jnp.float32)
    if bits == 4:
        q, scale, _ = quantize_blockwise_4bit_ref(shard32, block)
    else:
        q, scale, _ = quantize_blockwise_ref(shard32, block)
    q_all = jax.lax.all_gather(q, axis_name)  # [n, rows, wire-width]
    s_all = jax.lax.all_gather(scale, axis_name)
    rows = q_all.shape[0] * q_all.shape[1]
    q2 = q_all.reshape(rows, q_all.shape[2])
    s2 = s_all.reshape(rows, 1)
    if bits == 4:
        full = dequantize_blockwise_4bit_ref(q2, s2, (rows * block,))
    else:
        full = dequantize_blockwise_ref(q2, s2, (rows * block,))
    out = full.reshape(-1)[:size].reshape(shape) / n
    return out.astype(dtype)


def bucket_plan(
    leaves: Sequence, bucket_bytes: int
) -> List[List[int]]:
    """Greedy contiguous grouping of flat leaf indices into
    size-bounded, dtype-homogeneous buckets (concatenation needs one
    dtype per bucket; flatten order is the tree's canonical leaf
    order, so the plan is deterministic for a given pytree).

    A single leaf larger than ``bucket_bytes`` gets a bucket of its
    own — leaves are never split, so the bound is soft for oversized
    leaves and hard for everything else. Works on anything with
    ``.shape``/``.dtype`` (arrays, tracers, ShapeDtypeStructs), so
    the plan can be computed abstractly for accounting/metrics."""
    plan: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        nbytes = int(np.prod(leaf.shape)) * jnp.dtype(
            leaf.dtype
        ).itemsize
        if cur and (
            cur_dtype != leaf.dtype
            or cur_bytes + nbytes > bucket_bytes
        ):
            plan.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = leaf.dtype
    if cur:
        plan.append(cur)
    return plan


def bucketed_psum_mean(
    tree,
    axis_name: str,
    bucket_bytes: int = 4 << 20,
    bits: Optional[int] = None,
    block: int = 1024,
    min_size: int = DEFAULT_MIN_SIZE,
):
    """Mean-reduce a whole gradient pytree over ``axis_name`` as a
    sequence of size-bounded flat buckets instead of one collective
    per leaf (or one monolithic flatten).

    Why buckets: each bucket's psum is an *independent* collective
    whose result is consumed only by the accumulator add, so XLA's
    latency-hiding scheduler can run bucket k's reduce behind the
    compute that produces bucket k+1 — and, inside a scan over
    microbatches, behind the NEXT microbatch's backward. Per-leaf
    reduces of tiny tensors are latency-bound; a monolithic reduce
    serializes the whole sync after the last gradient materializes.
    ``bits`` of 4/8 routes buckets through
    :func:`compressed_psum_mean` (quantized all-gather phase); None
    keeps the sync exact. Must run inside shard_map."""
    leaves, treedef = jax.tree.flatten(tree)
    plan = bucket_plan(leaves, bucket_bytes)
    out = [None] * len(leaves)
    for idxs in plan:
        if len(idxs) == 1:
            flat = leaves[idxs[0]].reshape(-1)
        else:
            flat = jnp.concatenate(
                [leaves[i].reshape(-1) for i in idxs]
            )
        if bits is None:
            red = jax.lax.pmean(flat, axis_name)
        else:
            red = compressed_psum_mean(
                flat, axis_name, bits=bits, block=block,
                min_size=min_size,
            )
        off = 0
        for i in idxs:
            n = int(np.prod(leaves[i].shape))
            out[i] = red[off:off + n].reshape(leaves[i].shape)
            off += n
    return jax.tree.unflatten(treedef, out)


def make_compressed_train_step(
    mesh: Mesh,
    loss_fn: Callable,
    optimizer,
    axis_name: str = "data",
    bits: Optional[int] = 8,
    block: int = 1024,
    min_size: int = DEFAULT_MIN_SIZE,
    donate: bool = True,
    overlap: bool = False,
    bucket_mb: float = 4.0,
    accum_steps: int = 1,
):
    """Data-parallel train step whose gradient sync all-gathers
    quantized shards (replicated-params regime: every leaf is
    replicated over ``axis_name``, the batch is sharded over it).

    Drop-in for trainer.step.make_train_step on a pure-data mesh;
    compose the optimizer OUTSIDE the sync so its state stays exact.

    ``overlap=True`` switches the sync schedule from "one collective
    per leaf after backward" to size-bounded bucketed reduces issued
    as each bucket's gradients finalize (see
    :func:`bucketed_psum_mean`); with ``accum_steps > 1`` the step
    takes ``[accum, batch, ...]`` inputs and issues each microbatch's
    bucketed reduce *inside* the accumulation scan, so microbatch k's
    collective overlaps microbatch k+1's backward instead of paying
    one monolithic reduce after the loop. ``bits=None`` keeps the
    sync exact (overlap without quantization)."""
    if bits is not None and bits not in (4, 8):
        raise ValueError("bits must be 4, 8, or None (exact sync)")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if accum_steps > 1 and not overlap:
        raise ValueError(
            "accum_steps > 1 requires overlap=True (the serial "
            "accumulate-then-reduce shape lives in "
            "trainer.elastic_trainer)"
        )
    batch_spec = (
        P(None, axis_name) if accum_steps > 1 else P(axis_name)
    )
    rep = P()
    bucket_bytes = int(bucket_mb * (1 << 20))

    def leaf_sync(g):
        if bits is None:
            return jax.lax.pmean(g, axis_name)
        return compressed_psum_mean(
            g, axis_name, bits=bits, block=block, min_size=min_size
        )

    def sharded_grads(params, tokens, targets):
        if not overlap:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, targets
            )
            grads = jax.tree.map(leaf_sync, grads)
            loss = jax.lax.pmean(loss, axis_name)
            return loss, grads
        # Overlapped: per-microbatch bucketed reduce inside the scan.
        mb_tok = tokens if accum_steps > 1 else tokens[None]
        mb_tgt = targets if accum_steps > 1 else targets[None]

        def micro(carry, batch):
            grad_acc, loss_acc = carry
            t, y = batch
            loss, grads = jax.value_and_grad(loss_fn)(params, t, y)
            reduced = bucketed_psum_mean(
                jax.tree.map(lambda g: g / accum_steps, grads),
                axis_name,
                bucket_bytes=bucket_bytes,
                bits=bits,
                block=block,
                min_size=min_size,
            )
            grad_acc = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype), grad_acc, reduced
            )
            return (grad_acc, loss_acc + loss), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (grads, loss_sum), _ = jax.lax.scan(
            micro, (zeros, 0.0), (mb_tok, mb_tgt)
        )
        loss = jax.lax.pmean(loss_sum / accum_steps, axis_name)
        return loss, grads

    grads_fn = shard_map(
        sharded_grads,
        mesh=mesh,
        in_specs=(rep, batch_spec, batch_spec),
        out_specs=(rep, rep),
        check_vma=False,
    )

    def step(params, opt_state, tokens, targets):
        loss, grads = grads_fn(params, tokens, targets)
        # Same metrics contract as trainer.step.make_train_step — a
        # caller reading metrics["grad_norm"] must not crash only when
        # the search picks an overlap/compressed strategy.
        gnorm = optax.global_norm(grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def make_overlapped_train_step(
    mesh: Mesh,
    loss_fn: Callable,
    optimizer,
    axis_name: str = "data",
    accum_steps: int = 1,
    bucket_mb: float = 4.0,
    bits: Optional[int] = None,
    block: int = 1024,
    min_size: int = DEFAULT_MIN_SIZE,
    donate: bool = True,
):
    """Overlapped bucketed-reduce train step — the exact-sync (or,
    with ``bits``, compressed) schedule Strategy's ``overlap_reduce``
    knob selects. See :func:`make_compressed_train_step` with
    ``overlap=True``."""
    return make_compressed_train_step(
        mesh,
        loss_fn,
        optimizer,
        axis_name=axis_name,
        bits=bits,
        block=block,
        min_size=min_size,
        donate=donate,
        overlap=True,
        bucket_mb=bucket_mb,
        accum_steps=accum_steps,
    )


def sync_bytes_per_element(bits: Optional[int]) -> float:
    """Bytes moved per gradient element for a bf16 gradient sync —
    used by tests and capacity planning. Baseline allreduce = RS + AG
    at 2 B/el each = 4 B/el. Compressed: RS stays bf16 (2 B/el), AG
    drops to bits/8 B/el (+ per-block scales, amortized to ~0).
    ``bits=None`` is the exact sync: the 4 B/el baseline."""
    if bits is None:
        return 4.0
    return 2.0 + bits / 8.0


def overlap_sync_bytes_per_element(
    bits: Optional[int], accum_steps: int = 1
) -> float:
    """Per-gradient-element bytes one *optimizer step* of the
    overlapped schedule moves: every one of the ``accum_steps``
    per-microbatch reduces pays :func:`sync_bytes_per_element`
    (that volume multiplier is the price of hiding the latency behind
    backward compute — int8 at accum 2 costs 6 B/el vs the serial
    exact step's 4 B/el, and the tradeoff only wins when the hidden
    latency exceeds the extra wire time)."""
    return sync_bytes_per_element(bits) * accum_steps
