"""Compressed gradient synchronization over a mesh axis.

Reference counterparts: the quantized-reduction CUDA kernels
(atorch/ops/csrc/quantization/quant_reduce.cu,
swizzled_quantize.cu) and ADP's gradient-compression DDP hooks
(atorch/data_parallel/adp.py). On TPU the equivalent lever is the
*collective schedule*, not a custom allreduce: an allreduce is a
reduce-scatter (which must stay high-precision — it sums) followed by
an all-gather (which is pure broadcast and compresses safely). This
module implements

    psum_mean = psum_scatter(bf16/f32)  ->  quantize shard
                -> all_gather(int8 + per-block scales) -> dequantize

cutting the all-gather phase to ~1/2 (int8 vs bf16) or ~1/4 (packed
int4) of the bytes — worth it exactly where the data axis crosses DCN
(multi-slice outer axis, parallel/mesh.py), which is also where the
reference deployed gradient compression.

Opt-in via ``make_compressed_train_step`` for the replicated-params
data-parallel regime; per-leaf quantization error is bounded by the
per-block absmax / 127 (or /7 at 4 bits), and tests bound the
end-to-end gradient deviation.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

# Deliberately the jnp (_ref) quantizers, NOT the Pallas kernels:
# inside shard_map XLA fuses these elementwise ops straight into the
# collective schedule (quantize overlaps the reduce-scatter epilogue),
# whereas a pallas_call is an opaque boundary XLA cannot fuse or
# overlap through. Wire format (int8 / packed-nibble uint8 + f32
# per-block scales) is identical to the kernel path by construction —
# test_int4_wire_format_is_packed pins that.
from dlrover_tpu.ops.quantization import (
    dequantize_blockwise_4bit_ref,
    dequantize_blockwise_ref,
    quantize_blockwise_4bit_ref,
    quantize_blockwise_ref,
)

# Below this many elements the collective is latency-bound and
# padding to n*block would inflate tiny leaves (biases, norms) by
# orders of magnitude — plain pmean wins.
DEFAULT_MIN_SIZE = 16384


def compressed_psum_mean(
    x: jax.Array,
    axis_name: str,
    bits: int = 8,
    block: int = 1024,
    min_size: int = DEFAULT_MIN_SIZE,
) -> jax.Array:
    """Mean of ``x`` over ``axis_name`` with an int-quantized
    all-gather phase (packed two-per-byte at 4 bits — the
    ops/quantization.py wire format). Must run inside shard_map;
    returns the mean replicated across the axis (like ``lax.pmean``).
    Leaves smaller than ``min_size`` fall back to plain pmean.
    """
    if bits not in (4, 8):
        raise ValueError("bits must be 4 or 8")
    if x.size < min_size:
        return jax.lax.pmean(x, axis_name)
    n = jax.lax.psum(1, axis_name)
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)  # keep input dtype: RS bytes match baseline
    size = flat.size
    pad = (-size) % (n * block)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunk = flat.size // n
    # Phase 1: reduce-scatter in the gradient dtype (sums must not
    # quantize; same precision/bytes as the baseline psum's RS phase).
    shard = jax.lax.psum_scatter(
        flat.reshape(n, chunk), axis_name, scatter_dimension=0,
        tiled=False,
    )  # [chunk], this device's reduced shard
    # Phase 2: quantize the reduced shard, broadcast cheaply.
    shard32 = shard.astype(jnp.float32)
    if bits == 4:
        q, scale, _ = quantize_blockwise_4bit_ref(shard32, block)
    else:
        q, scale, _ = quantize_blockwise_ref(shard32, block)
    q_all = jax.lax.all_gather(q, axis_name)  # [n, rows, wire-width]
    s_all = jax.lax.all_gather(scale, axis_name)
    rows = q_all.shape[0] * q_all.shape[1]
    q2 = q_all.reshape(rows, q_all.shape[2])
    s2 = s_all.reshape(rows, 1)
    if bits == 4:
        full = dequantize_blockwise_4bit_ref(q2, s2, (rows * block,))
    else:
        full = dequantize_blockwise_ref(q2, s2, (rows * block,))
    out = full.reshape(-1)[:size].reshape(shape) / n
    return out.astype(dtype)


def make_compressed_train_step(
    mesh: Mesh,
    loss_fn: Callable,
    optimizer,
    axis_name: str = "data",
    bits: int = 8,
    block: int = 1024,
    min_size: int = DEFAULT_MIN_SIZE,
    donate: bool = True,
):
    """Data-parallel train step whose gradient sync all-gathers
    quantized shards (replicated-params regime: every leaf is
    replicated over ``axis_name``, the batch is sharded over it).

    Drop-in for trainer.step.make_train_step on a pure-data mesh;
    compose the optimizer OUTSIDE the sync so its state stays exact.
    """
    batch_spec = P(axis_name)
    rep = P()

    def sharded_grads(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, targets
        )
        sync = functools.partial(
            compressed_psum_mean, axis_name=axis_name, bits=bits,
            block=block, min_size=min_size,
        )
        grads = jax.tree.map(sync, grads)
        loss = jax.lax.pmean(loss, axis_name)
        return loss, grads

    grads_fn = shard_map(
        sharded_grads,
        mesh=mesh,
        in_specs=(rep, batch_spec, batch_spec),
        out_specs=(rep, rep),
        check_vma=False,
    )

    def step(params, opt_state, tokens, targets):
        loss, grads = grads_fn(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def sync_bytes_per_element(bits: int) -> float:
    """Bytes moved per gradient element for a bf16 gradient sync —
    used by tests and capacity planning. Baseline allreduce = RS + AG
    at 2 B/el each = 4 B/el. Compressed: RS stays bf16 (2 B/el), AG
    drops to bits/8 B/el (+ per-block scales, amortized to ~0)."""
    return 2.0 + bits / 8.0
