"""Ring attention: sequence/context parallelism over a mesh axis.

Supersedes the reference's blockwise distributed attention
(atorch/modules/distributed_transformer/distributed_attention.py:21-186:
allgathered micro-Q + global-softmax allreduce + reduce-scattered
context, overlapped on a second CUDA stream). The TPU-idiomatic design
instead keeps Q resident and rotates K/V blocks around the ``seq`` mesh
axis with ``lax.ppermute`` (ICI neighbor hops), merging each block with
a numerically-stable *online softmax* — communication volume is O(seq)
per device independent of world size, and XLA overlaps the permute with
the block matmuls.

Use :func:`ring_attention` inside ``shard_map`` (or via
:func:`make_sharded_attention` which wraps it).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from dlrover_tpu.parallel.shard_map_compat import shard_map


def _block_attn(q, k, v, scale, mask):
    """Scores + weighted values for one K/V block.

    q: [b, lq, h, d]; k/v: [b, lk, h, d]; mask broadcastable to
    [b, h, lq, lk] (True = keep). Returns (scores_max, exp_scores_sum,
    out_unnormalized) for online-softmax merging, all float32.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    s = s * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [b,h,q]
    # Guard fully-masked rows (causal ring blocks entirely in the
    # future): exp(-inf - -inf) would be NaN.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)  # [b,h,q]
    o = jnp.einsum(
        "bhqk,bkhd->bqhd",
        p.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return m_safe, l, o


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Attention where q/k/v are sharded over ``axis_name`` on the
    sequence dimension. Shapes (per-device): [batch, seq_local, heads,
    head_dim]. Must run inside shard_map with ``axis_name`` unmapped.

    ``window`` (requires ``causal=True``) applies the sliding-window
    band by masking only — every ring step still runs, so this XLA
    fallback is correct but O(T^2/shards); the flash path
    (:func:`ring_attention_flash`) statically skips band-dead ring
    steps and is the one to use for long windowed sequences.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (d**0.5)
    if window is not None and not causal:
        raise ValueError(
            "window (sliding-window attention) requires causal=True"
        )
    expand_kv = _gqa_expander(h, k.shape[2])

    q_pos = my_idx * lq + jnp.arange(lq)  # global query positions

    def step(carry, t):
        k_blk, v_blk, m_acc, l_acc, o_acc = carry
        src_idx = (my_idx - t) % n  # where this K/V block originated
        if causal:
            kv_pos = src_idx * lk + jnp.arange(lk)
            mask = q_pos[None, None, :, None] >= kv_pos[None, None, None, :]
            if window is not None:
                mask &= (
                    q_pos[None, None, :, None] - kv_pos[None, None, None, :]
                ) < window
        else:
            mask = None
        m_blk, l_blk, o_blk = _block_attn(
            q, expand_kv(k_blk), expand_kv(v_blk), scale, mask
        )
        # Online-softmax merge of block stats into the accumulator.
        m_new = jnp.maximum(m_acc, m_blk)
        corr_acc = jnp.exp(m_acc - m_new)
        corr_blk = jnp.exp(m_blk - m_new)
        l_new = l_acc * corr_acc + l_blk * corr_blk
        o_new = (
            o_acc * corr_acc.transpose(0, 2, 1)[..., None]
            + o_blk * corr_blk.transpose(0, 2, 1)[..., None]
        )
        # Rotate K/V to the next ring position (ICI neighbor hop).
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new), None

    m0 = jnp.full((b, h, lq), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, lq), dtype=jnp.float32)
    o0 = jnp.zeros((b, lq, h, d), dtype=jnp.float32)
    (_, _, m_f, l_f, o_f), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n)
    )
    l_f = jnp.maximum(l_f, 1e-20)  # fully-masked rows divide by ~0
    out = o_f / l_f.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


_NEG = -1e30  # "-inf" that keeps exp/logaddexp NaN-free


def _gqa_expander(h_q: int, h_kv: int):
    """Grouped-query support for the ring families: K/V ride the ring
    COMPACT (h_kv heads — 1/q_per_kv the ppermute bytes of the
    expanded layout models used to pre-broadcast) and are broadcast
    over their query group only at the per-block kernel call, where
    XLA folds the repeat into the kernel's input copy. Returns the
    per-block expansion fn."""
    if h_kv == h_q:
        return lambda x: x
    if h_q % h_kv:
        raise ValueError(
            f"grouped-query attention needs q heads ({h_q}) divisible "
            f"by kv heads ({h_kv})"
        )
    g = h_q // h_kv
    return lambda x: jnp.repeat(x, g, axis=2)


def ring_attention_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the per-block
    engine: each ring step runs flash attention against the resident
    K/V block (O(lq) memory — the [lq, lk] score tile never reaches
    HBM, unlike :func:`ring_attention`'s XLA path) and merges the
    normalized block output via its logsumexp. This is the Ring
    Attention construction (blockwise-parallel ring, PAPERS.md) with
    the inner block computed by ops/flash_attention.py, including its
    lse-cotangent backward.

    Causal runs dispatch one of three per-block programs: K/V from an
    earlier ring slot attends densely, the resident slot runs the
    causal kernel, later slots are skipped (zero compute beyond the
    branch). Per-device work is therefore imbalanced by ring position
    — inherent to causal ring attention.

    ``window`` (requires ``causal=True``) runs Mistral-style
    sliding-window attention with a STATICALLY truncated ring: a K/V
    block at ring distance t spans key offsets [t*lq - lq + 1,
    t*lq + lq - 1] from its queries, so once (t-1)*lq + 1 > window-1
    the block is outside the band for EVERY device and the schedule
    stops — both compute and ppermute hops truncate to
    t_stop = min(n-1, (window + lq - 2) // lq), giving
    O(T * window / shards) work and O(window) communication per
    device instead of O(T^2/shards) / O(T). Live non-resident steps
    run the rectangular banded kernel (flash_attention_rect with
    q_offset = t*lq) at exact cost.
    """
    from dlrover_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_rect,
    )

    b, lq, h, d = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (d**0.5)
    if window is not None:
        if not causal:
            raise ValueError(
                "window (sliding-window attention) requires causal=True"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if window >= n * lq:
            window = None  # band covers the global sequence
    expand_kv = _gqa_expander(h, k.shape[2])

    def flash_blk(q_, k_, v_, causal_):
        o, lse = flash_attention(
            q_, expand_kv(k_), expand_kv(v_), causal=causal_,
            scale=scale, interpret=interpret, return_lse=True,
        )
        return o.astype(jnp.float32), lse

    if window is not None:
        return _ring_flash_windowed(
            q, k, v, axis_name, int(window), scale, interpret,
            flash_attention, flash_attention_rect,
        )

    def step(carry, t):
        k_blk, v_blk, lse_acc, o_acc = carry
        src = (my_idx - t) % n
        if causal:
            idx = jnp.where(src < my_idx, 0, jnp.where(src == my_idx, 1, 2))
            o_blk, lse_blk = jax.lax.switch(
                idx,
                [
                    lambda q_, k_, v_: flash_blk(q_, k_, v_, False),
                    lambda q_, k_, v_: flash_blk(q_, k_, v_, True),
                    lambda q_, k_, v_: (
                        jnp.zeros((b, lq, h, d), jnp.float32),
                        jnp.full((b, h, lq), _NEG, jnp.float32),
                    ),
                ],
                q, k_blk, v_blk,
            )
        else:
            o_blk, lse_blk = flash_blk(q, k_blk, v_blk, False)
        lse_new = jnp.logaddexp(lse_acc, lse_blk)
        w_acc = jnp.exp(lse_acc - lse_new)
        w_blk = jnp.exp(lse_blk - lse_new)
        o_new = (
            o_acc * w_acc.transpose(0, 2, 1)[..., None]
            + o_blk * w_blk.transpose(0, 2, 1)[..., None]
        )
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, lse_new, o_new), None

    lse0 = jnp.full((b, h, lq), _NEG, jnp.float32)
    o0 = jnp.zeros((b, lq, h, d), jnp.float32)
    (_, _, _, o_f), _ = jax.lax.scan(
        step, (k, v, lse0, o0), jnp.arange(n)
    )
    return o_f.astype(q.dtype)


def _ring_flash_windowed(
    q, k, v, axis_name, window, scale, interpret,
    flash_attention, flash_attention_rect,
):
    """Sliding-window causal ring (see ring_attention_flash docstring).

    The loop over ring distance t is a STATIC Python loop (n is the
    static mesh-axis size), so the band-dead tail of the ring —
    distances with (t-1)*lq + 1 > window-1 — is never traced at all:
    no flash calls, no ppermute hops. Per live step:

    * t = 0: the resident block, square causal+window kernel;
    * t >= 1: the block sits at static key offset t*lq below the
      queries — devices with my_idx >= t run the banded rectangular
      kernel (q_offset = t*lq makes the causal compare inactive and
      the window compare exact); devices with my_idx < t would
      receive a wrapped FUTURE block, and contribute zeros via
      lax.cond. (Per the SPMD cond caveat on ring_prefix_lm_attention,
      XLA may compute both branches and select — correctness is
      unaffected; the static truncation above is where the asymptotic
      saving lives and it does not depend on cond lowering.)
    """
    b, lq, h, d = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    t_stop = min(n - 1, (window + lq - 2) // lq)
    expand_kv = _gqa_expander(h, k.shape[2])

    zeros = (
        jnp.zeros((b, lq, h, d), jnp.float32),
        jnp.full((b, h, lq), _NEG, jnp.float32),
    )

    def resident(q_, k_, v_):
        o, lse = flash_attention(
            q_, expand_kv(k_), expand_kv(v_), causal=True,
            window=window, scale=scale, interpret=interpret,
            return_lse=True,
        )
        return o.astype(jnp.float32), lse

    def banded(q_, k_, v_, off):
        o, lse = flash_attention_rect(
            q_, expand_kv(k_), expand_kv(v_), causal=True,
            q_offset=off, window=window, scale=scale,
            interpret=interpret, return_lse=True,
        )
        return o.astype(jnp.float32), lse

    lse_acc = jnp.full((b, h, lq), _NEG, jnp.float32)
    o_acc = jnp.zeros((b, lq, h, d), jnp.float32)
    k_blk, v_blk = k, v
    for t in range(t_stop + 1):
        if t == 0:
            o_blk, lse_blk = resident(q, k_blk, v_blk)
        else:
            o_blk, lse_blk = jax.lax.cond(
                my_idx >= t,
                lambda q_, k_, v_, t=t: banded(q_, k_, v_, t * lq),
                lambda *_: zeros,
                q, k_blk, v_blk,
            )
        lse_new = jnp.logaddexp(lse_acc, lse_blk)
        w_acc = jnp.exp(lse_acc - lse_new)
        w_blk = jnp.exp(lse_blk - lse_new)
        o_acc = (
            o_acc * w_acc.transpose(0, 2, 1)[..., None]
            + o_blk * w_blk.transpose(0, 2, 1)[..., None]
        )
        lse_acc = lse_new
        if t < t_stop:
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return o_acc.astype(q.dtype)


def make_sharded_attention(
    mesh: Mesh,
    causal: bool = True,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    impl: str = "auto",
    window: Optional[int] = None,
):
    """Wrap ring attention in shard_map for the given mesh.

    Sequence parallelism composes with tensor parallelism: heads are
    sharded over ``tensor`` while sequence blocks ride the ``seq`` ring.

    ``impl``: "flash" uses the Pallas per-block kernel
    (ring_attention_flash), "xla" the einsum path (ring_attention),
    "auto" picks flash on TPU.

    ``window`` (requires ``causal=True``) applies Mistral-style
    sliding-window attention on every path: the flash ring statically
    skips band-dead ring hops (O(T*window/shards) work), the XLA ring
    masks, and the single-shard fallbacks pass it to the kernel.
    """
    if impl not in ("auto", "flash", "xla"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    if window is not None and not causal:
        raise ValueError(
            "window (sliding-window attention) requires causal=True"
        )
    use_flash = (
        impl == "flash"
        or (impl == "auto" and jax.default_backend() == "tpu")
    )
    spec = P(batch_axes, axis_name, head_axis, None)

    if mesh.shape.get(axis_name, 1) == 1:
        if use_flash:
            from dlrover_tpu.ops.flash_attention import flash_attention

            return _expand_kv_wrapper(
                functools.partial(
                    flash_attention, causal=causal, window=window
                )
            )

        # No sequence sharding: plain (still jit-fused) attention —
        # the one definition of the dense causal/window mask lives in
        # gpt._default_attention (ulysses.py's degenerate path ends
        # here too).
        from dlrover_tpu.models.gpt import _default_attention

        return _expand_kv_wrapper(
            functools.partial(
                _default_attention, causal=causal, window=window
            )
        )

    fn = functools.partial(
        ring_attention_flash if use_flash else ring_attention,
        axis_name=axis_name,
        causal=causal,
        window=window,
    )
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    tp = mesh.shape.get(head_axis, 1) if head_axis is not None else 1

    def attn(q, k, v):
        # Compact K/V needs its head dim to split over the tensor
        # axis; when it can't (h_kv < tensor shards), pre-broadcast —
        # correct, just without the traffic saving.
        if k.shape[2] != q.shape[2] and k.shape[2] % tp:
            expand = _gqa_expander(q.shape[2], k.shape[2])
            k, v = expand(k), expand(v)
        return sharded(q, k, v)

    # Models may pass COMPACT grouped-query K/V (h_kv < h heads): the
    # ring rotates the small tensors and broadcasts per block.
    attn.supports_gqa = True
    return attn


def _expand_kv_wrapper(fn):
    """Equal-heads kernels behind a constructor that advertises
    grouped-query support: broadcast compact K/V over the query
    groups right before the call (XLA folds the repeat into the
    kernel's input transpose/copy)."""

    def attn(q, k, v, **kw):
        expand = _gqa_expander(q.shape[2], k.shape[2])
        return fn(q, expand(k), expand(v), **kw)

    attn.supports_gqa = True
    return attn


def ring_prefix_lm_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    prefix_len: int,
    axis_name: str = "seq",
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    attn_blocks: Optional[tuple] = None,
) -> jax.Array:
    """GLM prefix-LM attention with the sequence sharded over a ring.

    ONE fused ring scan with two online-softmax accumulators, keeping
    the SPMD program uniform across devices (per-device static row
    splits would break shard_map):

    * the CAUSAL accumulator collects each block under the causal
      ring schedule (earlier slot: dense; resident slot: causal
      kernel; later: skip) — the exact result for suffix rows, whose
      prefix keys are a subset of their causal keys;
    * the PREFIX accumulator collects the same blocks under the
      prefix-bidirectional schedule: blocks before the boundary
      attend densely, the ONE block containing the boundary (index
      ``prefix_len // block`` — static) contributes through a
      static-shape rectangular flash call over its first
      ``prefix_len % block`` keys, later blocks are skipped;
    * rows at global position < prefix_len take the prefix result,
      the rest the causal one.

    K/V rotate the ring ONCE; a block needed densely by both
    accumulators is computed once and merged twice. Worst-case cost
    is under 2x a plain causal ring step — the price of
    sequence-sharding a mask the collectives can't express directly;
    single-shard GLM uses the exact-cost composition in
    ops/prefix_lm.py.

    Cost caveat (unverified on hardware): the ``lax.cond``/
    ``lax.switch`` predicates here depend on the traced
    ``axis_index``, and under SPMD partitioning XLA may lower such
    conditionals to compute-both-branches + select rather than a real
    branch. If it does, the skip/dense gating saves nothing and a
    worst-case step costs up to dense + causal + rect per slot (~3x a
    causal ring step) in FLOPs — still correct, and still O(T^2 /
    shards) memory, but the FLOP saving advertised above should be
    confirmed with a per-op profile on a real chip before relying on
    it (tools/profile_step.py). A masking-based schedule (zeroing
    contributions instead of branching) would make the cost explicit
    and uniform if profiling shows both branches execute.

    ``prefix_len`` is the GLOBAL prefix length (static), validated
    against the global sequence n * block.
    """
    from dlrover_tpu.ops.flash_attention import (
        blocks_kwargs,
        flash_attention,
        flash_attention_rect,
    )

    b, lq, h, d = q.shape
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (d**0.5)
    expand_kv = _gqa_expander(h, k.shape[2])
    p = int(prefix_len)
    if not 0 <= p <= n * lq:
        raise ValueError(
            f"prefix_len={p} outside [0, {n * lq}] (global seq = "
            f"{n} ring blocks x {lq})"
        )
    bkw = blocks_kwargs(attn_blocks)
    if p == 0:
        return ring_attention_flash(
            q, k, v, axis_name, causal=True, scale=scale,
            interpret=interpret,
        )

    b_p = p // lq   # the ring block containing the boundary (static)
    rem = p - b_p * lq  # prefix keys inside that block (static)

    zeros = (
        jnp.zeros((b, lq, h, d), jnp.float32),
        jnp.full((b, h, lq), _NEG, jnp.float32),
    )

    def dense_blk(q_, k_, v_):
        o, lse = flash_attention(
            q_, expand_kv(k_), expand_kv(v_), causal=False,
            scale=scale, interpret=interpret, return_lse=True, **bkw,
        )
        return o.astype(jnp.float32), lse

    def causal_blk(q_, k_, v_):
        o, lse = flash_attention(
            q_, expand_kv(k_), expand_kv(v_), causal=True,
            scale=scale, interpret=interpret, return_lse=True, **bkw,
        )
        return o.astype(jnp.float32), lse

    def rect_blk(q_, k_, v_):
        o, lse = flash_attention_rect(
            q_, expand_kv(k_[:, :rem]), expand_kv(v_[:, :rem]),
            causal=False, q_offset=0, scale=scale,
            interpret=interpret, return_lse=True,
        )
        return o.astype(jnp.float32), lse

    def merge(acc, blk):
        lse_acc, o_acc = acc
        o_blk, lse_blk = blk
        lse_new = jnp.logaddexp(lse_acc, lse_blk)
        w_acc = jnp.exp(lse_acc - lse_new)
        w_blk = jnp.exp(lse_blk - lse_new)
        o_new = (
            o_acc * w_acc.transpose(0, 2, 1)[..., None]
            + o_blk * w_blk.transpose(0, 2, 1)[..., None]
        )
        return lse_new, o_new

    def step(carry, t):
        k_blk, v_blk, acc_c, acc_p = carry
        src = (my_idx - t) % n
        # The dense block value is shared: computed once when EITHER
        # schedule needs it (causal: src < my_idx; prefix: src < b_p).
        need_dense = jnp.logical_or(src < my_idx, src < b_p)
        dense = jax.lax.cond(
            need_dense, dense_blk, lambda *_: zeros, q, k_blk, v_blk
        )

        # Causal accumulator: dense for earlier slots, the causal
        # kernel on the resident slot, skip for later slots.
        c_idx = jnp.where(
            src < my_idx, 0, jnp.where(src == my_idx, 1, 2)
        )
        blk_c = jax.lax.switch(
            c_idx,
            [lambda: dense, lambda: causal_blk(q, k_blk, v_blk),
             lambda: zeros],
        )
        acc_c = merge(acc_c, blk_c)

        # Prefix accumulator: dense before the boundary block, the
        # rectangular slice on it (when it has prefix keys), skip
        # after.
        if rem > 0:
            p_idx = jnp.where(
                src < b_p, 0, jnp.where(src == b_p, 1, 2)
            )
            blk_p = jax.lax.switch(
                p_idx,
                [lambda: dense, lambda: rect_blk(q, k_blk, v_blk),
                 lambda: zeros],
            )
        else:
            p_idx = jnp.where(src < b_p, 0, 1)
            blk_p = jax.lax.switch(
                p_idx, [lambda: dense, lambda: zeros]
            )
        acc_p = merge(acc_p, blk_p)

        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc_c, acc_p), None

    acc0 = (
        jnp.full((b, h, lq), _NEG, jnp.float32),
        jnp.zeros((b, lq, h, d), jnp.float32),
    )
    (_, _, (_, o_causal), (_, o_prefix)), _ = jax.lax.scan(
        step, (k, v, acc0, acc0), jnp.arange(n)
    )

    pos = my_idx * lq + jnp.arange(lq)  # global row positions
    take_prefix = (pos < p)[None, :, None, None]
    return jnp.where(take_prefix, o_prefix, o_causal).astype(q.dtype)


def make_sharded_prefix_attention(
    mesh: Mesh,
    prefix_len: int,
    axis_name: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
    attn_blocks: Optional[tuple] = None,
):
    """Prefix-LM attention for a mesh — the GLM analogue of
    :func:`make_sharded_attention`. With ``seq`` sharding it runs the
    fused two-accumulator ring (:func:`ring_prefix_lm_attention`);
    without, the exact-cost single-shard composition
    (ops/prefix_lm.py). ``attn_blocks`` threads the tuned flash
    tiles through either path (model configs carry it)."""
    if mesh.shape.get(axis_name, 1) == 1:
        from dlrover_tpu.ops.prefix_lm import prefix_lm_attention

        return functools.partial(
            prefix_lm_attention, prefix_len=prefix_len,
            attn_blocks=attn_blocks,
        )
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = functools.partial(
        ring_prefix_lm_attention,
        prefix_len=prefix_len,
        axis_name=axis_name,
        attn_blocks=attn_blocks,
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
