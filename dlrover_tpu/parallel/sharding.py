"""Logical-axis sharding rules.

The TPU-native replacement for the reference's per-module parallel
wrappers (atorch RowParallelLinear/ColumnParallelLinear etc.,
modules/distributed_modules/layers.py): models annotate parameters with
*logical* axis names; a rule table maps logical names to mesh axes and
GSPMD propagates everything else. Changing the parallelism strategy is
a rule-table edit, not a model rewrite.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxis = Union[str, None, Tuple[str, ...]]
Rules = Dict[str, MeshAxis]

# Default rule table for transformer LMs. Logical names follow the
# usual conventions (batch/seq/embed/mlp/heads/kv/vocab).
DEFAULT_RULES: Rules = {
    "batch": ("data", "fsdp"),
    "seq": "seq",
    # Weight embed dim shards over fsdp (ZeRO-3-style); activations
    # annotate their embed dim as None.
    "embed": "fsdp",
    "mlp": "tensor",
    "heads": "tensor",
    "kv": None,
    "vocab": "tensor",
    "expert": "expert",
    "stage": "pipe",
    "layers": None,  # scanned layer stack dim stays replicated
}


def spec_for(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Rules] = None,
) -> P:
    """Translate logical axis names to a PartitionSpec."""
    rules = rules or DEFAULT_RULES
    return P(*(rules.get(a) if a else None for a in logical_axes))


def tree_specs(logical_tree, rules: Optional[Rules] = None):
    """Map a pytree of logical-axes tuples to PartitionSpecs."""
    return jax.tree.map(
        lambda axes: spec_for(axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(a, (str, type(None))) for a in x),
    )


def tree_shardings(
    mesh: Mesh, logical_tree, rules: Optional[Rules] = None
):
    specs = tree_specs(logical_tree, rules)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def prune_specs_to_mesh(mesh: Mesh, specs):
    """Drop mesh axes of size 1 from specs (XLA treats them as
    replicated anyway, but pruning keeps HLO shardings tidy)."""

    def prune(spec: P) -> P:
        out = []
        for entry in spec:
            if entry is None:
                out.append(None)
            elif isinstance(entry, tuple):
                kept = tuple(
                    a for a in entry if mesh.shape.get(a, 1) > 1
                )
                out.append(kept if kept else None)
            else:
                out.append(
                    entry if mesh.shape.get(entry, 1) > 1 else None
                )
        return P(*out)

    return jax.tree.map(
        prune, specs, is_leaf=lambda x: isinstance(x, P)
    )


def shard_array(mesh: Mesh, spec: P, x):
    return jax.device_put(x, NamedSharding(mesh, spec))
