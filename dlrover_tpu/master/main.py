"""Master CLI entrypoint: ``python -m dlrover_tpu.master.main``.

Parity: dlrover/python/master/main.py:37-58.
"""

from __future__ import annotations

import argparse
import sys

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.master import JobMaster

logger = get_logger("master.main")


def parse_args(argv=None):
    parser = argparse.ArgumentParser("dlrover-tpu-master")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--node_num", type=int, default=1)
    parser.add_argument("--min_nodes", type=int, default=0)
    parser.add_argument("--node_unit", type=int, default=1)
    parser.add_argument("--rdzv_timeout", type=float, default=30.0)
    # Sparse/CTR jobs: enable hot-PS migration + worker adjustment
    # (master/auto_scaler.py:PsTrainingAutoScaler).
    parser.add_argument("--ps_autoscale", action="store_true")
    parser.add_argument(
        "--ps_autoscale_interval", type=float, default=30.0
    )
    # Workers whose permanent loss fails the job: "", "none", "all",
    # or "rank:budget,..." (ref: critical-nodes spec,
    # master/node/training_node.py:81).
    parser.add_argument("--critical_workers", type=str, default="")
    # Standalone evaluator nodes the master schedules; the trainer's
    # evaluate loop attaches to them (role: NodeType.EVALUATOR).
    parser.add_argument("--evaluator_count", type=int, default=0)
    # Node-death detection knobs (drills/tests tighten these; the
    # defaults match production pod-failure budgets).
    parser.add_argument(
        "--heartbeat_timeout", type=float, default=180.0
    )
    parser.add_argument(
        "--monitor_interval", type=float, default=30.0
    )
    # Prometheus text exposition: GET /metrics on this port (0 =
    # ephemeral, printed as DLROVER_TPU_METRICS_PORT=N; unset = no
    # HTTP endpoint — metrics stay reachable over the MetricsRequest
    # RPC either way).
    parser.add_argument("--metrics_port", type=int, default=None)
    parser.add_argument("--job_name", type=str, default="")
    # Master warm restart: journal recoverable state (node table,
    # rendezvous round/world, shard ledger, kv store, speed progress)
    # into this directory and restore from the newest valid snapshot
    # at startup. Also settable via DLROVER_TPU_STATE_DIR.
    parser.add_argument("--state_dir", type=str, default=None)
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    # Black box before anything can crash: the master process's
    # flight recorder (crash bundles + WARNING+ log ring). Installed
    # here, not in JobMaster.prepare(), so in-process test harnesses
    # never get their excepthooks rewired implicitly.
    from dlrover_tpu import obs

    obs.install_flight_recorder("master")
    try:
        master = JobMaster(
            port=args.port,
            node_num=args.node_num,
            min_nodes=args.min_nodes,
            node_unit=args.node_unit,
            rdzv_timeout=args.rdzv_timeout,
            critical_workers=args.critical_workers,
            evaluator_count=args.evaluator_count,
            heartbeat_timeout=args.heartbeat_timeout,
            monitor_interval=args.monitor_interval,
            job_name=args.job_name,
            metrics_port=args.metrics_port,
            state_dir=args.state_dir,
        )
    except ValueError as exc:
        logger.error("invalid arguments: %s", exc)
        return 2
    master.prepare()
    if args.ps_autoscale:
        master.start_ps_autoscaler(interval=args.ps_autoscale_interval)
    # Print the bound port on stdout so a parent process can discover it.
    print(f"DLROVER_TPU_MASTER_PORT={master.port}", flush=True)
    if master.metrics_server is not None:
        print(
            f"DLROVER_TPU_METRICS_PORT={master.metrics_server.port}",
            flush=True,
        )
    return master.run()


if __name__ == "__main__":
    sys.exit(main())
