"""Auto-scaler + local resource optimizer.

Capability parity with the reference's resource re-planning layer
(dlrover/python/master/node/job_auto_scaler.py:40
``new_job_auto_scaler`` / AllreduceTrainingAutoScaler :254, and
master/resource/local_optimizer.py:66): periodically compare the
job's target worker count with what is actually alive, grow OOM'd
nodes' memory before relaunch, and — for TPU — keep the worker count
on *slice-compatible* sizes (a v5p slice wants multiples of its host
count; arbitrary worker counts strand chips).

The Brain remote optimizer of the reference (brain_optimizer.py) is a
pluggable ResourceOptimizer here; LocalResourceOptimizer is the
default heuristic (the reference ships the same split).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.job_manager import JobManager, ScalePlan
from dlrover_tpu.master.speed_monitor import SpeedMonitor

logger = get_logger("auto_scaler")

_SCALE_PLANS = obs.counter(
    "dlrover_autoscale_plans_total",
    "Scale plans issued by the auto-scalers",
    ("kind",),
)

OOM_MEMORY_GROW_FACTOR = 1.5  # ref local_optimizer.py:96 grows OOM pods


class ResourceOptimizer:
    """Strategy seam: local heuristic now, Brain-style remote later."""

    def optimize_oom_node(self, resource: NodeResource) -> NodeResource:
        raise NotImplementedError

    def target_worker_count(
        self, current: int, speed_monitor: SpeedMonitor
    ) -> int:
        raise NotImplementedError


class LocalResourceOptimizer(ResourceOptimizer):
    def __init__(
        self,
        min_workers: int = 1,
        max_workers: int = 64,
        hosts_per_slice: int = 1,
    ):
        self.min_workers = min_workers
        self.max_workers = max_workers
        # TPU slices come in fixed host multiples (v5p-32 = 4 hosts);
        # scaling to a non-multiple strands chips.
        self.hosts_per_slice = max(hosts_per_slice, 1)

    def optimize_oom_node(self, resource: NodeResource) -> NodeResource:
        grown = NodeResource.from_dict(resource.to_dict())
        grown.memory_mb = int(
            max(resource.memory_mb, 1024) * OOM_MEMORY_GROW_FACTOR
        )
        return grown

    def target_worker_count(
        self, current: int, speed_monitor: SpeedMonitor
    ) -> int:
        target = max(self.min_workers, min(current, self.max_workers))
        # round DOWN to a slice multiple (never exceed what is alive)
        target -= target % self.hosts_per_slice
        return max(target, self.hosts_per_slice)


class PsLocalOptimizer:
    """Runtime-stats-driven resource planning for the PS (sparse/CTR)
    strategy — capability parity with the reference's PSLocalOptimizer
    (dlrover/python/master/resource/local_optimizer.py:66):

    * hot-PS: a PS whose averaged CPU utilisation over the sample
      window crosses ``ps_cpu_hot_threshold`` should be migrated to a
      node with more CPU (``optimize_hot_ps``), scaled by the same
      tune-factor rule the reference uses (bounded by node_max_cpu).
    * worker count: while the hottest PS still has CPU headroom below
      ``ps_cpu_overload_threshold``, workers can grow by the headroom
      factor (ref local_optimizer.py:189 _generate_worker_resoruce) —
      gated on the *marginal speed ratio* of the last worker change
      (ref :249 _compute_worker_speed_ratio): if adding workers no
      longer yields ≥ ``min_worker_speed_ratio`` of linear speedup,
      stop growing.
    """

    def __init__(
        self,
        ps_cpu_hot_threshold: float = 0.9,
        ps_cpu_overload_threshold: float = 0.7,
        min_worker_speed_ratio: float = 0.4,
        node_max_cpu: float = 32.0,
        max_workers: int = 64,
        window: int = 5,
    ):
        self.ps_cpu_hot_threshold = ps_cpu_hot_threshold
        self.ps_cpu_overload_threshold = ps_cpu_overload_threshold
        self.min_worker_speed_ratio = min_worker_speed_ratio
        self.node_max_cpu = node_max_cpu
        self.max_workers = max_workers
        self.window = window
        # ps_id -> recent cpu-percent samples (0..100)
        self._ps_cpu: dict = {}
        # (worker_num, speed) history for the marginal-speedup gate
        self._speed_hist: List[tuple] = []

    # -- sample collection ----------------------------------------------

    def record_ps_sample(self, ps_id: int, cpu_percent: float) -> None:
        hist = self._ps_cpu.setdefault(ps_id, [])
        hist.append(cpu_percent)
        del hist[: -self.window]

    def record_speed_sample(self, worker_num: int, speed: float) -> None:
        if speed > 0:
            self._speed_hist.append((worker_num, speed))
            del self._speed_hist[: -10 * self.window]

    def forget_ps(self, ps_id: int) -> None:
        self._ps_cpu.pop(ps_id, None)

    # -- plans -----------------------------------------------------------

    def _avg_cpu(self, ps_id: int) -> float:
        hist = self._ps_cpu.get(ps_id) or [0.0]
        return sum(hist) / len(hist)

    def hot_ps(self) -> List[int]:
        return sorted(
            ps_id
            for ps_id in self._ps_cpu
            if self._avg_cpu(ps_id) / 100.0 >= self.ps_cpu_hot_threshold
        )

    def optimize_hot_ps(
        self, config_cpu: dict
    ) -> dict:
        """Plan CPU growth for hot PS nodes. ``config_cpu`` maps ps_id
        to its currently-configured CPU cores; returns ps_id -> new
        cpu for nodes that should migrate to a bigger node. Mirrors the
        reference's tune-factor: grow toward node_max_cpu but never
        shrink (local_optimizer.py:299 _optimize_hot_ps_cpu)."""
        plan = {}
        for ps_id in self.hot_ps():
            cur = config_cpu.get(ps_id, 1.0) or 1.0
            used = cur * self._avg_cpu(ps_id) / 100.0
            factor = min(self.node_max_cpu / max(used, 0.1), 2.0)
            opt = round(used * factor, 1)
            if opt > cur:
                plan[ps_id] = min(opt, self.node_max_cpu)
        return plan

    def worker_speed_ratio(self) -> float:
        """Marginal per-worker speedup of the most recent worker-count
        change, relative to the average speed per worker before it.
        1.0 when no change has happened yet (nothing to judge)."""
        hist = self._speed_hist
        if len(hist) < 2:
            return 1.0
        post_num = hist[-1][0]
        split = len(hist)
        for i in reversed(range(len(hist))):
            if hist[i][0] != post_num:
                split = i + 1
                break
        if split == len(hist):  # worker count never changed
            return 1.0
        post = [s for n, s in hist[split:] if n == post_num]
        pre_num = hist[split - 1][0]
        pre = [s for n, s in hist[:split] if n == pre_num]
        if not pre or not post or pre_num == post_num:
            return 1.0
        pre_speed = sum(pre) / len(pre)
        post_speed = sum(post) / len(post)
        worker_diff = post_num - pre_num
        if worker_diff <= 0 or pre_speed <= 0:
            return 1.0
        marginal = (post_speed - pre_speed) / worker_diff
        linear = pre_speed / pre_num
        return marginal / linear if linear > 0 else 1.0

    def optimize_worker_count(self, current: int) -> int:
        """Target worker count from PS CPU headroom: with the hottest
        PS at util u < overload threshold o, workers can scale by o/u
        (ref local_optimizer.py:213). Gated on the marginal-speedup
        ratio so a PS-bound or input-bound job stops growing, and on
        having real throughput evidence at all — with no speed samples
        the gate must fail CLOSED, not open."""
        if current <= 0:
            return current
        if len(self._speed_hist) < self.window:
            return current
        utils = [self._avg_cpu(p) / 100.0 for p in self._ps_cpu]
        max_util = max(utils, default=0.0)
        if max_util >= self.ps_cpu_overload_threshold or max_util <= 0:
            return current
        if self.worker_speed_ratio() < self.min_worker_speed_ratio:
            return current
        factor = self.ps_cpu_overload_threshold / max_util
        return min(int(current * factor), self.max_workers)


class PsTrainingAutoScaler:
    """Auto-scaler for the PS (sparse embedding) strategy — parity with
    the reference's PSTrainingAutoScaler
    (dlrover/python/master/node/job_auto_scaler.py:98) on the
    TPU-native PS fabric (master/ps_manager.py):

    * hot-PS migration: launch a replacement EMBEDDING node with grown
      CPU; when it registers with the PsManager, the old node is
      drained (partitions move via the minimal-move rebalance) and
      removed — the TPU-native analogue of
      ps.py:327 _migrate_parameter_server.
    * worker adjustment: grow the worker group while PS CPU headroom
      and the marginal speed ratio allow (PsLocalOptimizer).
    """

    def __init__(
        self,
        job_manager: JobManager,
        speed_monitor: SpeedMonitor,
        ps_manager,
        optimizer: Optional[PsLocalOptimizer] = None,
        interval: float = 30.0,
    ):
        self.job_manager = job_manager
        self.speed_monitor = speed_monitor
        self.ps_manager = ps_manager
        self.optimizer = optimizer or PsLocalOptimizer()
        self.interval = interval
        # old_ps_id -> replacement node id, pending the replacement's
        # registration with the PsManager
        self._migrations: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ps-auto-scaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.adjust_once()
            except Exception:  # noqa: BLE001
                logger.warning("ps auto-scale pass failed", exc_info=True)

    # -- one adjustment pass --------------------------------------------

    def _collect(self) -> None:
        fresh = self.ps_manager.stats(max_age=3 * self.interval)
        for ps_id, stats in fresh.items():
            self.optimizer.record_ps_sample(ps_id, stats.cpu_percent)
        workers = [
            n
            for n in self.job_manager.list_nodes(NodeType.WORKER)
            if n.is_alive()
        ]
        self.optimizer.record_speed_sample(
            len(workers), self.speed_monitor.running_speed()
        )

    def adjust_once(self) -> Optional[ScalePlan]:
        self._collect()
        self._finish_migrations()
        plan = self._migrate_hot_ps()
        if plan is not None:
            return plan
        return self._adjust_workers()

    # -- hot-PS migration -----------------------------------------------

    def _ps_nodes(self) -> dict:
        """ps_id -> job Node (ids translated out of the EMBEDDING
        node-id namespace, constants.ps_node_id)."""
        from dlrover_tpu.common.constants import node_ps_id

        return {
            node_ps_id(n.id): n
            for n in self.job_manager.list_nodes(NodeType.EMBEDDING)
            if not n.status == NodeStatus.DELETED
        }

    def _migrate_hot_ps(self) -> Optional[ScalePlan]:
        from dlrover_tpu.common.constants import ps_node_id

        nodes = self._ps_nodes()
        config_cpu = {
            ps_id: (n.config_resource.cpu if n.config_resource else 1.0)
            for ps_id, n in nodes.items()
        }
        growth = self.optimizer.optimize_hot_ps(config_cpu)
        plan = ScalePlan()
        next_ps_id = (
            max(
                list(nodes) + list(self._migrations.values()),
                default=-1,
            )
            + 1
        )
        for old_id, new_cpu in growth.items():
            if old_id in self._migrations or old_id not in nodes:
                continue
            old = nodes[old_id]
            resource = (
                NodeResource.from_dict(old.config_resource.to_dict())
                if old.config_resource
                else NodeResource()
            )
            resource.cpu = new_cpu
            repl = Node(
                type=NodeType.EMBEDDING,
                id=ps_node_id(next_ps_id),
                rank=old.rank,
                status=NodeStatus.PENDING,
                config_resource=resource,
            )
            self._migrations[old_id] = next_ps_id
            next_ps_id += 1
            plan.launch_nodes.append(repl)
            logger.info(
                "hot PS %d (cpu %.1f) -> migrating to ps %d with "
                "cpu %.1f",
                old_id,
                config_cpu.get(old_id, 0.0),
                self._migrations[old_id],
                new_cpu,
            )
        if not plan.launch_nodes:
            return None
        for node in plan.launch_nodes:
            self.job_manager.adopt_node(node)
        self.job_manager.scaler.scale(plan)
        _SCALE_PLANS.inc(kind="ps_hot_migration")
        obs.event(
            "autoscale.plan",
            kind="ps_hot_migration",
            launch=[n.id for n in plan.launch_nodes],
        )
        return plan

    def _finish_migrations(self) -> None:
        """Once a replacement PS has registered with the PsManager
        (it appears in the partition map), drain and retire the old
        node. A replacement that died before registering (pending
        timeout, launch failure) releases the migration slot so the
        still-hot PS can be retried."""
        if not self._migrations:
            return
        from dlrover_tpu.common.constants import ps_node_id

        registered = set(self.ps_manager.partition_map.ps_addrs)
        for old_id, new_id in list(self._migrations.items()):
            if new_id in registered:
                # the old PS is still alive: drain (live PS-to-PS
                # move), don't treat it as dead
                self.ps_manager.drain_ps(old_id)
                self.optimizer.forget_ps(old_id)
                self.job_manager.retire_node(ps_node_id(old_id))
                del self._migrations[old_id]
                logger.info(
                    "hot-PS migration %d -> %d complete", old_id, new_id
                )
                continue
            repl_node = self.job_manager.get_node(ps_node_id(new_id))
            if (
                repl_node is not None
                and repl_node.status in NodeStatus.TERMINAL
            ):
                del self._migrations[old_id]
                logger.warning(
                    "hot-PS migration %d -> %d abandoned (replacement "
                    "%s); will retry", old_id, new_id, repl_node.status,
                )

    # -- worker adjustment ----------------------------------------------

    def _adjust_workers(self) -> Optional[ScalePlan]:
        workers = [
            n
            for n in self.job_manager.list_nodes(NodeType.WORKER)
            if n.is_alive()  # ALIVE includes PENDING
        ]
        target = self.optimizer.optimize_worker_count(len(workers))
        missing = target - len(workers)
        if missing <= 0:
            return None
        next_id = (
            max(
                [n.id for n in self.job_manager.list_nodes()],
                default=-1,
            )
            + 1
        )
        template = workers[0] if workers else None
        plan = ScalePlan()
        for i in range(missing):
            resource = (
                NodeResource.from_dict(
                    template.config_resource.to_dict()
                )
                if template is not None and template.config_resource
                else NodeResource()
            )
            plan.launch_nodes.append(
                Node(
                    type=NodeType.WORKER,
                    id=next_id + i,
                    rank=next_id + i,
                    status=NodeStatus.PENDING,
                    config_resource=resource,
                )
            )
        for node in plan.launch_nodes:
            self.job_manager.adopt_node(node)
        self.job_manager.scaler.scale(plan)
        logger.info(
            "ps-strategy worker adjust: %d -> %d", len(workers), target
        )
        _SCALE_PLANS.inc(kind="ps_worker_adjust")
        obs.event(
            "autoscale.plan",
            kind="ps_worker_adjust",
            current=len(workers), target=target,
        )
        return plan


class AllreduceAutoScaler:
    """Keeps an allreduce (SPMD) job at its target size (ref
    AllreduceTrainingAutoScaler._periodic_adjust_worker
    job_auto_scaler.py:288): counts alive workers, asks the scaler for
    replacements of anything missing, and applies OOM memory growth
    to relaunch resources."""

    def __init__(
        self,
        job_manager: JobManager,
        speed_monitor: SpeedMonitor,
        target_workers: int,
        optimizer: Optional[ResourceOptimizer] = None,
        interval: float = 30.0,
        num_slices: int = 1,
    ):
        self.job_manager = job_manager
        self.speed_monitor = speed_monitor
        self.target_workers = target_workers
        self.optimizer = optimizer or LocalResourceOptimizer()
        self.interval = interval
        # Multi-slice jobs: replacements must land in the deficient
        # slice so the DCN (outer) mesh axis stays balanced.
        self.num_slices = max(num_slices, 1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="auto-scaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.adjust_once()
            except Exception:  # noqa: BLE001
                logger.warning("auto-scale pass failed", exc_info=True)

    def grow_oom_resources(self) -> None:
        """Apply memory growth to nodes that OOM'd before their
        replacement launches."""
        for node in self.job_manager.list_nodes(NodeType.WORKER):
            if (
                node.relaunch_reason == "oom"
                and node.status == NodeStatus.PENDING
                and node.config_resource is not None
                and not getattr(node, "_oom_grown", False)
            ):
                node.config_resource = self.optimizer.optimize_oom_node(
                    node.config_resource
                )
                node._oom_grown = True  # type: ignore[attr-defined]
                logger.info(
                    "node %d OOM relaunch memory grown to %dMB",
                    node.id,
                    node.config_resource.memory_mb,
                )

    def adjust_once(self) -> Optional[ScalePlan]:
        """One pass: replace missing workers up to the slice-aligned
        target. Returns the plan if one was issued."""
        self.grow_oom_resources()
        nodes = self.job_manager.list_nodes(NodeType.WORKER)
        # ALIVE includes PENDING: replacements in flight count toward
        # the target (counting them twice would strand the job one
        # worker short of the target forever). Cordoned nodes do NOT
        # count (alive_workers excludes them): the remediation engine
        # deliberately benched them, and "fixing" the deficit by
        # counting the benched host would leave the job short a
        # healthy worker.
        alive = self.job_manager.alive_workers()
        target = self.optimizer.target_worker_count(
            self.target_workers, self.speed_monitor
        )
        missing = target - len(alive)
        if missing <= 0:
            return None

        # Fill the most-deficient slice first so the DCN axis stays
        # balanced (each slice is one block of the outer mesh axis).
        def slice_of(n: Node) -> int:
            if n.config_resource is None:
                return 0
            return max(n.config_resource.slice_id, 0) % self.num_slices

        counts = {s: 0 for s in range(self.num_slices)}
        templates: dict = {}
        for n in alive:
            s = slice_of(n)
            counts[s] += 1
            templates.setdefault(s, n)
        fallback = alive[0] if alive else (nodes[0] if nodes else None)

        used_ids = {n.id for n in nodes}
        plan = ScalePlan()
        next_id = max(used_ids, default=-1) + 1
        for i in range(missing):
            s = min(counts, key=counts.get)
            counts[s] += 1
            template = templates.get(s, fallback)
            resource = (
                NodeResource.from_dict(
                    template.config_resource.to_dict()
                )
                if template is not None and template.config_resource
                else NodeResource()
            )
            # pin only when the job actually spans slices
            resource.slice_id = s if self.num_slices > 1 else -1
            plan.launch_nodes.append(
                Node(
                    type=NodeType.WORKER,
                    id=next_id + i,
                    rank=next_id + i,
                    status=NodeStatus.PENDING,
                    config_resource=resource,
                )
            )
        for node in plan.launch_nodes:
            self.job_manager.adopt_node(node)
        self.job_manager.scaler.scale(plan)
        _SCALE_PLANS.inc(kind="allreduce_replace")
        obs.event(
            "autoscale.plan",
            kind="allreduce_replace",
            alive=len(alive), target=target, missing=missing,
        )
        logger.info(
            "auto-scaler: %d alive of target %d -> launching %d "
            "(slices %s)",
            len(alive),
            target,
            missing,
            {s: c for s, c in counts.items()} if self.num_slices > 1
            else "n/a",
        )
        return plan
