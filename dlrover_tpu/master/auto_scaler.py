"""Auto-scaler + local resource optimizer.

Capability parity with the reference's resource re-planning layer
(dlrover/python/master/node/job_auto_scaler.py:40
``new_job_auto_scaler`` / AllreduceTrainingAutoScaler :254, and
master/resource/local_optimizer.py:66): periodically compare the
job's target worker count with what is actually alive, grow OOM'd
nodes' memory before relaunch, and — for TPU — keep the worker count
on *slice-compatible* sizes (a v5p slice wants multiples of its host
count; arbitrary worker counts strand chips).

The Brain remote optimizer of the reference (brain_optimizer.py) is a
pluggable ResourceOptimizer here; LocalResourceOptimizer is the
default heuristic (the reference ships the same split).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.job_manager import JobManager, ScalePlan
from dlrover_tpu.master.speed_monitor import SpeedMonitor

logger = get_logger("auto_scaler")

OOM_MEMORY_GROW_FACTOR = 1.5  # ref local_optimizer.py:96 grows OOM pods


class ResourceOptimizer:
    """Strategy seam: local heuristic now, Brain-style remote later."""

    def optimize_oom_node(self, resource: NodeResource) -> NodeResource:
        raise NotImplementedError

    def target_worker_count(
        self, current: int, speed_monitor: SpeedMonitor
    ) -> int:
        raise NotImplementedError


class LocalResourceOptimizer(ResourceOptimizer):
    def __init__(
        self,
        min_workers: int = 1,
        max_workers: int = 64,
        hosts_per_slice: int = 1,
    ):
        self.min_workers = min_workers
        self.max_workers = max_workers
        # TPU slices come in fixed host multiples (v5p-32 = 4 hosts);
        # scaling to a non-multiple strands chips.
        self.hosts_per_slice = max(hosts_per_slice, 1)

    def optimize_oom_node(self, resource: NodeResource) -> NodeResource:
        grown = NodeResource.from_dict(resource.to_dict())
        grown.memory_mb = int(
            max(resource.memory_mb, 1024) * OOM_MEMORY_GROW_FACTOR
        )
        return grown

    def target_worker_count(
        self, current: int, speed_monitor: SpeedMonitor
    ) -> int:
        target = max(self.min_workers, min(current, self.max_workers))
        # round DOWN to a slice multiple (never exceed what is alive)
        target -= target % self.hosts_per_slice
        return max(target, self.hosts_per_slice)


class AllreduceAutoScaler:
    """Keeps an allreduce (SPMD) job at its target size (ref
    AllreduceTrainingAutoScaler._periodic_adjust_worker
    job_auto_scaler.py:288): counts alive workers, asks the scaler for
    replacements of anything missing, and applies OOM memory growth
    to relaunch resources."""

    def __init__(
        self,
        job_manager: JobManager,
        speed_monitor: SpeedMonitor,
        target_workers: int,
        optimizer: Optional[ResourceOptimizer] = None,
        interval: float = 30.0,
    ):
        self.job_manager = job_manager
        self.speed_monitor = speed_monitor
        self.target_workers = target_workers
        self.optimizer = optimizer or LocalResourceOptimizer()
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="auto-scaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.adjust_once()
            except Exception:  # noqa: BLE001
                logger.warning("auto-scale pass failed", exc_info=True)

    def grow_oom_resources(self) -> None:
        """Apply memory growth to nodes that OOM'd before their
        replacement launches."""
        for node in self.job_manager.list_nodes(NodeType.WORKER):
            if (
                node.relaunch_reason == "oom"
                and node.status == NodeStatus.PENDING
                and node.config_resource is not None
                and not getattr(node, "_oom_grown", False)
            ):
                node.config_resource = self.optimizer.optimize_oom_node(
                    node.config_resource
                )
                node._oom_grown = True  # type: ignore[attr-defined]
                logger.info(
                    "node %d OOM relaunch memory grown to %dMB",
                    node.id,
                    node.config_resource.memory_mb,
                )

    def adjust_once(self) -> Optional[ScalePlan]:
        """One pass: replace missing workers up to the slice-aligned
        target. Returns the plan if one was issued."""
        self.grow_oom_resources()
        nodes = self.job_manager.list_nodes(NodeType.WORKER)
        alive = [n for n in nodes if n.is_alive()]
        pending = [n for n in nodes if n.status == NodeStatus.PENDING]
        target = self.optimizer.target_worker_count(
            self.target_workers, self.speed_monitor
        )
        missing = target - len(alive) - len(pending)
        if missing <= 0:
            return None
        used_ids = {n.id for n in nodes}
        plan = ScalePlan()
        next_id = max(used_ids, default=-1) + 1
        template = alive[0] if alive else (nodes[0] if nodes else None)
        for i in range(missing):
            resource = (
                NodeResource.from_dict(
                    template.config_resource.to_dict()
                )
                if template is not None and template.config_resource
                else NodeResource()
            )
            plan.launch_nodes.append(
                Node(
                    type=NodeType.WORKER,
                    id=next_id + i,
                    rank=next_id + i,
                    status=NodeStatus.PENDING,
                    config_resource=resource,
                )
            )
        for node in plan.launch_nodes:
            self.job_manager.adopt_node(node)
        self.job_manager.scaler.scale(plan)
        logger.info(
            "auto-scaler: %d alive / %d pending of target %d -> "
            "launching %d",
            len(alive),
            len(pending),
            target,
            missing,
        )
        return plan
