"""Self-healing control plane: verdict-driven remediation with
safety governors.

The health plane (obs/health.py) *diagnoses*: detectors over the
time-series history emit typed :class:`HealthVerdict` s. This engine
*acts* on them — DLRover's brain loop closed (PAPER.md §1.1, ROADMAP
item 2) — through the seams the control plane already has:

========================  ==================================================
critical verdict          remediation action
========================  ==================================================
throughput_degradation,   **cordon-then-replace**: mark the host cordoned
straggler_persistence     (it leaves the rendezvous at the next boundary,
                          its agent parks the trainer on the ``cordon``
                          heartbeat action), launch a replacement worker
                          via a ScalePlan, and only *retire* the cordoned
                          pod once probation confirms recovery — so a
                          wrong conviction is reversible.
recompile_storm,          **restart_training**: bounce the wedged/leaking
rss_growth,               trainer in place through the heartbeat action
data_starvation           FIFO (the agent restarts the process, the node
                          stays).
(sick past budget)        **shrink**: when replace didn't help (probation
                          rolled back) and the host is convicted again,
                          retire it without a replacement — the world
                          shrinks at the next rendezvous boundary, never
                          below ``min_nodes``.
replica_unhealthy         **serving ladder** (docs/SERVING.md): first
                          **drain_replica** (the router requeues its
                          in-flight requests — requests are safe within
                          one decision), then if the replica stays
                          convicted **restart_training** (its agent
                          bounces the replica process), then
                          **cordon_replace** (a fresh replica node via
                          ScalePlan). Training peers are never bounced
                          for a replica subject.
========================  ==================================================

The *governors* are the point of this module — every action must pass
all of them, and every decision (acted, blocked, dry-run) is an
auditable record:

* **hysteresis** — a subject must be critical for N *consecutive*
  engine ticks before any action (a flapping host is damped, never
  ping-pongs the world), and recovery needs M consecutive healthy
  ticks before probation declares success;
* **cooldown** — decorrelated (jittered) per-subject cooldowns shared
  with the health plane's PROFILE/DIAGNOSE action stamps
  (``HealthMonitor.action_stamp``), so capture and remediation never
  hammer the same subject together;
* **blast radius** — at most ``blast_max_actions`` (default 1) acted
  remediations per ``blast_window_s`` fleet-wide, and cordon/shrink
  never take the live world below ``min_nodes``;
* **probation** — after acting, the engine watches for
  ``probation_s``: recovery (verdict resolved + throughput back
  within ``recover_ratio``) finalizes the action; a failed probation
  *rolls back* (un-cordon, retire the replacement, stop relaunching)
  or *escalates* one rung (restart → cordon-replace → shrink →
  alert-only);
* **dry-run** — ``DLROVER_TPU_REMEDIATION_DRY_RUN=1`` evaluates the
  full pipeline and persists the decisions without mutating anything.

Decisions are exported as ``dlrover_remediation_*`` metrics, traced
as ``remediation.*`` events, persisted to the brain datastore
(``remediation_decisions`` table), served over the
``RemediationQueryRequest`` RPC, journaled into master state
snapshots (a warm restart keeps cordons, probations, and history),
and rendered by ``tools/obs_report.py --health``.

Every knob reads ``DLROVER_TPU_REMEDIATION_<KNOB>`` (see DEFAULTS),
overridable per-instance via ``config=``; the clock and RNG are
injectable so every governor is hermetically testable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.constants import (
    EventAction,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs import tracer as _trace
from dlrover_tpu.obs.health import SEVERITY_CRITICAL, HealthVerdict

logger = get_logger("remediation")

REMEDIATION_ENV_PREFIX = "DLROVER_TPU_REMEDIATION_"

ACTION_RESTART_TRAINING = "restart_training"
ACTION_CORDON_REPLACE = "cordon_replace"
ACTION_SHRINK = "shrink"
ACTION_DRAIN_REPLICA = "drain_replica"
ACTION_ALERT_ONLY = "alert_only"

# Escalation ladder rungs, per subject: the base action, then
# cordon-replace, then shrink, then alert-only. A successful probation
# resets the subject to the base rung; a failed one advances it.
RUNG_BASE = 0
RUNG_CORDON = 1
RUNG_SHRINK = 2
RUNG_ALERT_ONLY = 3

# Which critical detector convicts into which base action. Detectors
# absent here (goodput_slo = job-wide, fleet_stall = nobody to
# convict, heartbeat_gap = a silent node cannot be handed an action)
# stay alert-only by design.
DETECTOR_ACTIONS: Dict[str, str] = {
    "throughput_degradation": ACTION_CORDON_REPLACE,
    "straggler_persistence": ACTION_CORDON_REPLACE,
    # The stall correlator's localized culprit: replace the one wedged
    # host, never blind-restart the fleet it parked.
    "collective_stall": ACTION_CORDON_REPLACE,
    "recompile_storm": ACTION_RESTART_TRAINING,
    "rss_growth": ACTION_RESTART_TRAINING,
    "data_starvation": ACTION_RESTART_TRAINING,
    "replica_unhealthy": ACTION_DRAIN_REPLICA,
}

# Serving subjects climb their OWN ladder, indexed by the same rung
# counter: drain (requests requeue) -> restart (the agent bounces the
# replica process) -> replace (fresh replica node via ScalePlan) ->
# alert-only.
SERVING_LADDER = (
    ACTION_DRAIN_REPLICA,
    ACTION_RESTART_TRAINING,
    ACTION_CORDON_REPLACE,
    ACTION_ALERT_ONLY,
)

OUTCOME_PENDING = "pending"
OUTCOME_ACTED = "acted"
OUTCOME_DRY_RUN = "dry_run"
OUTCOME_BLOCKED = "blocked"
OUTCOME_FAILED = "failed"
OUTCOME_RECOVERED = "recovered"
OUTCOME_ROLLED_BACK = "rolled_back"
OUTCOME_ESCALATED = "escalated"

_DECISIONS_TOTAL = obs.counter(
    "dlrover_remediation_decisions_total",
    "Remediation decisions recorded by the master's engine, by "
    "detector, action, and (transitioning) outcome",
    ("detector", "action", "outcome"),
)
_GOVERNOR_BLOCKS = obs.counter(
    "dlrover_remediation_governor_blocks_total",
    "Remediation actions vetoed by a safety governor",
    ("governor",),
)
_CORDONED_NODES = obs.gauge(
    "dlrover_remediation_cordoned_nodes",
    "Nodes currently cordoned (excluded from rendezvous, replacement "
    "in flight, retirement pending probation)",
)
_PROBATIONS_ACTIVE = obs.gauge(
    "dlrover_remediation_probations_active",
    "Remediation actions currently inside their post-action "
    "probation window",
)
_RECOVERY_SECONDS = obs.gauge(
    "dlrover_remediation_recovery_seconds",
    "Decision-to-recovery duration of the most recently RECOVERED "
    "remediation (verdict-convicted action through probation "
    "success)",
)

# Every governor knob, with its default. Override per knob via
# DLROVER_TPU_REMEDIATION_<NAME-upper> or the config= dict (config
# wins). Windows are seconds; tick counts are engine ticks.
DEFAULTS: Dict[str, float] = {
    "enabled": 1.0,
    "dry_run": 0.0,
    "interval_s": 15.0,
    # hysteresis: N consecutive critical ticks to act, M consecutive
    # healthy ticks for probation to declare recovery
    "hysteresis_ticks": 3.0,
    "recovery_ticks": 3.0,
    # blast radius: acted remediations per window, fleet-wide
    "blast_window_s": 600.0,
    "blast_max_actions": 1.0,
    # per-subject cooldown, shared with the health plane's action
    # stamps; jitter decorrelates subjects that got sick together
    "cooldown_s": 300.0,
    "cooldown_jitter": 0.5,
    # probation: how long to watch after acting, and how close to the
    # verdict's own pre-degradation baseline throughput must return
    "probation_s": 300.0,
    "recover_ratio": 1.25,
    "history": 256.0,
}


@dataclasses.dataclass
class RemediationDecision:
    """One engine decision — the auditable record the acceptance
    criteria demand: trigger verdict + evidence pointer, the result of
    every governor check, the action, and the eventual outcome."""

    decision_id: int
    detector: str
    severity: str
    node_id: int
    host: str
    action: str
    trigger: str  # the convicting verdict's message
    governors: Dict[str, str] = dataclasses.field(default_factory=dict)
    outcome: str = OUTCOME_PENDING
    dry_run: bool = False
    # The verdict's own healthy baseline (metrics["baseline_mean_s"]),
    # the yardstick probation measures recovery against.
    baseline_step_s: float = 0.0
    timestamp: float = 0.0
    probation_deadline: float = 0.0
    healthy_ticks: int = 0
    resolved_at: float = 0.0
    replacement_id: int = -1
    note: str = ""
    # Distributed trace: one trace per decision (verdict -> governors
    # -> action -> probation -> outcome spans; a drain's requeues link
    # in), span_id its root span.
    trace_id: str = ""
    span_id: str = ""

    def subject(self) -> Tuple[str, int]:
        return (self.host, self.node_id)

    def to_dict(self) -> dict:
        return {
            "decision_id": self.decision_id,
            "detector": self.detector,
            "severity": self.severity,
            "node_id": self.node_id,
            "host": self.host,
            "action": self.action,
            "trigger": self.trigger,
            "governors": dict(self.governors),
            "outcome": self.outcome,
            "dry_run": self.dry_run,
            "baseline_step_s": round(self.baseline_step_s, 6),
            "timestamp": round(self.timestamp, 3),
            "probation_deadline": round(self.probation_deadline, 3),
            "healthy_ticks": self.healthy_ticks,
            "resolved_at": round(self.resolved_at, 3),
            "replacement_id": self.replacement_id,
            "note": self.note,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RemediationDecision":
        return cls(
            decision_id=int(d.get("decision_id", 0)),
            detector=str(d.get("detector", "")),
            severity=str(d.get("severity", "")),
            node_id=int(d.get("node_id", -1)),
            host=str(d.get("host", "")),
            action=str(d.get("action", "")),
            trigger=str(d.get("trigger", "")),
            governors={
                str(k): str(v)
                for k, v in (d.get("governors") or {}).items()
            },
            outcome=str(d.get("outcome", OUTCOME_PENDING)),
            dry_run=bool(d.get("dry_run", False)),
            baseline_step_s=float(d.get("baseline_step_s", 0.0)),
            timestamp=float(d.get("timestamp", 0.0)),
            probation_deadline=float(d.get("probation_deadline", 0.0)),
            healthy_ticks=int(d.get("healthy_ticks", 0)),
            resolved_at=float(d.get("resolved_at", 0.0)),
            replacement_id=int(d.get("replacement_id", -1)),
            note=str(d.get("note", "")),
            trace_id=str(d.get("trace_id", "")),
            span_id=str(d.get("span_id", "")),
        )


GOVERNOR_OK = "ok"


class RemediationEngine:
    """Consumes the health plane's active verdicts on a cadence and
    drives governed, reversible recovery actions through the master's
    existing seams (job manager + scaler, servicer action FIFO,
    rendezvous managers).

    Everything is injectable for hermetic tests: ``clock`` drives
    windows/probations, ``rng_seed`` fixes the decorrelating jitter,
    and the collaborating components are plain constructor args.
    """

    def __init__(
        self,
        health,
        job_manager,
        servicer,
        fleet=None,
        store=None,
        speed_monitor=None,
        auto_scaler=None,
        rdzv_managers: Sequence = (),
        serving=None,
        brain=None,
        traces=None,
        min_nodes: int = 1,
        job_name: str = "default",
        clock: Optional[Callable[[], float]] = None,
        config: Optional[Dict[str, float]] = None,
        interval: Optional[float] = None,
        rng_seed: int = 0,
    ):
        self.health = health
        self.job_manager = job_manager
        self.servicer = servicer
        self.fleet = fleet
        self.store = store
        self.speed_monitor = speed_monitor
        self.auto_scaler = auto_scaler
        self.rdzv_managers = tuple(rdzv_managers)
        # Serving router: the drain rung of the replica_unhealthy
        # ladder calls its drain_replica; None on training-only
        # masters (the detector then never fires either).
        self.serving = serving
        self.brain = brain
        # Trace store: every decision assembles a causal timeline
        # (verdict -> governors -> action -> probation -> outcome)
        # queryable by decision trace id or node subject.
        self.traces = traces
        self.min_nodes = max(int(min_nodes), 1)
        self.job_name = job_name
        self.clock = clock if clock is not None else time.time
        self._config = dict(config or {})
        self.interval = (
            interval if interval is not None else self._cfg("interval_s")
        )
        self._rng_seed = rng_seed
        self._lock = threading.Lock()
        self._seq = 0
        self._decisions: deque = deque(maxlen=int(self._cfg("history")))
        # (detector, host, node_id) -> consecutive critical ticks
        self._sick: Dict[Tuple[str, str, int], int] = {}
        # node_id -> cordon record (host, detector, decision_id,
        # replacement_id, since)
        self._cordoned: Dict[int, dict] = {}
        # decision_id -> decision under probation
        self._probation: Dict[int, RemediationDecision] = {}
        # (host, node_id) -> escalation rung (RUNG_ALERT_ONLY is the
        # terminal rung: the subject never draws another action)
        self._ladder: Dict[Tuple[str, int], int] = {}
        # Wall stamps of acted remediations inside the blast window.
        self._window: List[float] = []
        # Dedup for repeated records while a subject stays sick:
        # dry-run decisions and blocked decisions log once per episode
        # (re-armed when the subject's verdict resolves).
        self._logged: Dict[Tuple[str, str, int], str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Fired after decision/cordon/probation state changes; the
        # JobMaster points this at the state journal.
        self.on_state_change = None

    # -- config -----------------------------------------------------------

    def _cfg(self, knob: str) -> float:
        if knob in self._config:
            return float(self._config[knob])
        env = os.getenv(REMEDIATION_ENV_PREFIX + knob.upper(), "")
        if env:
            try:
                return float(env)
            except ValueError:
                logger.warning(
                    "bad %s%s=%r; using default %s",
                    REMEDIATION_ENV_PREFIX, knob.upper(), env,
                    DEFAULTS[knob],
                )
        return DEFAULTS[knob]

    @property
    def enabled(self) -> bool:
        return bool(self._cfg("enabled"))

    @property
    def dry_run(self) -> bool:
        return bool(self._cfg("dry_run"))

    # -- engine lifecycle --------------------------------------------------

    def start(self) -> None:
        if not self.enabled:
            logger.info("remediation engine disabled; not starting")
            return
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="remediation", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick_once()
            except Exception:  # noqa: BLE001 — an engine bug must not
                # kill the thread (and with it all future remediation)
                logger.warning("remediation tick failed", exc_info=True)

    def _changed(self) -> None:
        cb = self.on_state_change
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001
                pass

    # -- one evaluation tick ----------------------------------------------

    def tick_once(self) -> List[RemediationDecision]:
        """One engine tick: refresh hysteresis from the active verdict
        set, review probations (recovery / rollback / escalation),
        then evaluate new actions through the governor pipeline.
        Returns the decisions recorded this tick."""
        if not self.enabled:
            return []
        now = self.clock()
        critical = [
            v
            for v in self.health.active_verdicts()
            if v.severity == SEVERITY_CRITICAL
        ]
        crit_keys = {v.key() for v in critical}
        crit_subjects = {(v.host, v.node_id) for v in critical}
        with self._lock:
            for key in crit_keys:
                self._sick[key] = self._sick.get(key, 0) + 1
            for key in list(self._sick):
                if key not in crit_keys:
                    del self._sick[key]
                    self._logged.pop(key, None)
        recorded: List[RemediationDecision] = []
        recorded.extend(self._review_probations(now, crit_subjects))
        recorded.extend(self._decide(critical, now))
        _PROBATIONS_ACTIVE.set(len(self._probation))
        _CORDONED_NODES.set(len(self._cordoned))
        if recorded:
            self._changed()
        return recorded

    # -- governors ---------------------------------------------------------

    def _alive_workers(self) -> List:
        return self.job_manager.alive_workers()

    def _check_governors(
        self, v: HealthVerdict, action: str, now: float
    ) -> Dict[str, str]:
        """Every governor's verdict for this candidate action. All
        values ``"ok"`` means the action may proceed."""
        g: Dict[str, str] = {}
        key = v.key()
        sick = self._sick.get(key, 0)
        need = int(self._cfg("hysteresis_ticks"))
        g["hysteresis"] = (
            GOVERNOR_OK
            if sick >= need
            else f"blocked: {sick}/{need} consecutive sick ticks"
        )
        # Cooldown shared with the health plane's PROFILE/DIAGNOSE
        # stamps: one stamp map, decorrelated by jitter so subjects
        # convicted together do not act in lockstep.
        last = self.health.action_stamp(key)
        cooldown = self._cooldown_for(key)
        if last is not None and now - last < cooldown:
            g["cooldown"] = (
                f"blocked: {now - last:.0f}s since last action "
                f"< {cooldown:.0f}s cooldown"
            )
        else:
            g["cooldown"] = GOVERNOR_OK
        recent = [
            t for t in self._window
            if now - t < self._cfg("blast_window_s")
        ]
        max_actions = int(self._cfg("blast_max_actions"))
        g["blast_radius"] = (
            GOVERNOR_OK
            if len(recent) < max_actions
            else (
                f"blocked: {len(recent)} action(s) in the last "
                f"{self._cfg('blast_window_s'):.0f}s window "
                f"(cap {max_actions})"
            )
        )
        if action in (ACTION_CORDON_REPLACE, ACTION_SHRINK):
            if v.detector == "replica_unhealthy":
                # Replica subjects never shrink the TRAINING world
                # (min_nodes guards workers); serving capacity is
                # refilled by the replacement and requests queue at
                # the router meanwhile — the router's min_replicas
                # governs serving floor separately.
                g["min_nodes"] = GOVERNOR_OK
            else:
                alive = len(self._alive_workers())
                g["min_nodes"] = (
                    GOVERNOR_OK
                    if alive - 1 >= self.min_nodes
                    else (
                        f"blocked: {alive} alive worker(s) - 1 < "
                        f"min_nodes {self.min_nodes}"
                    )
                )
        if action == ACTION_CORDON_REPLACE:
            # Under a pool master this job's replacement must fit its
            # GRANT: cordon-then-replace briefly runs old + new side
            # by side, and the pool will not hand out a slice the
            # scheduler did not grant. Single-job masters (no grant)
            # pass unconditionally. getattr: embedded test doubles
            # predate the pool seam.
            headroom_fn = getattr(
                self.job_manager, "grant_headroom", None
            )
            headroom = headroom_fn() if headroom_fn else None
            g["pool_grant"] = (
                GOVERNOR_OK
                if headroom is None or headroom >= 1
                else (
                    "blocked: pool grant "
                    f"{self.job_manager.pool_grant} has no headroom "
                    "for a replacement"
                )
            )
        return g

    def _cooldown_for(self, key: Tuple[str, str, int]) -> float:
        """The subject's jittered cooldown threshold. Derived
        DETERMINISTICALLY from (rng_seed, subject key) — not re-rolled
        per tick: a fresh draw every governor check would let any
        subject pass as soon as one roll landed low (the min of
        repeated uniforms walks to zero), collapsing the promised
        decorrelation back into lockstep at ~cooldown_s. A stable
        per-subject draw also survives master restarts, so the
        spread keeps its meaning across a warm restart."""
        base = self._cfg("cooldown_s")
        jitter = self._cfg("cooldown_jitter")
        if jitter <= 0:
            return base
        digest = hashlib.sha256(
            f"{self._rng_seed}:{key!r}".encode()
        ).digest()
        r = int.from_bytes(digest[:8], "big") / 2.0**64
        return base * (1.0 + jitter * r)

    def _action_for(self, v: HealthVerdict) -> Optional[str]:
        subject = (v.host, v.node_id)
        base = DETECTOR_ACTIONS.get(v.detector)
        if base is None:
            return None
        rung = self._ladder.get(subject, RUNG_BASE)
        if v.detector == "replica_unhealthy":
            action = SERVING_LADDER[
                min(rung, len(SERVING_LADDER) - 1)
            ]
            return None if action == ACTION_ALERT_ONLY else action
        if rung >= RUNG_ALERT_ONLY:
            return None
        if rung >= RUNG_SHRINK:
            return ACTION_SHRINK
        if rung >= RUNG_CORDON or base == ACTION_CORDON_REPLACE:
            return ACTION_CORDON_REPLACE
        return base

    # -- decide + execute --------------------------------------------------

    def _decide(
        self, critical: List[HealthVerdict], now: float
    ) -> List[RemediationDecision]:
        recorded: List[RemediationDecision] = []
        for v in critical:
            if v.node_id < 0:
                continue  # job-wide or unmapped subject
            with self._lock:
                if v.node_id in self._cordoned:
                    continue  # already mid-remediation
                if any(
                    d.node_id == v.node_id
                    for d in self._probation.values()
                ):
                    continue
                action = self._action_for(v)
            if action is None:
                continue
            node = self.job_manager.get_node(v.node_id)
            if node is None or not node.is_alive():
                continue
            governors = self._check_governors(v, action, now)
            blocked = {
                name: why
                for name, why in governors.items()
                if why != GOVERNOR_OK
            }
            key = v.key()
            if blocked:
                # Hysteresis warming up is the normal path, not an
                # audit-worthy veto; other governors are.
                others = {
                    n for n in blocked if n != "hysteresis"
                }
                if not others or governors["hysteresis"] != GOVERNOR_OK:
                    continue
                mark = "blocked:" + ",".join(sorted(others))
                with self._lock:
                    if self._logged.get(key) == mark:
                        continue
                    self._logged[key] = mark
                for name in sorted(others):
                    _GOVERNOR_BLOCKS.inc(governor=name)
                d = self._new_decision(
                    v, action, governors, now,
                    outcome=OUTCOME_BLOCKED,
                )
                self._record(d)
                recorded.append(d)
                continue
            if self.dry_run:
                mark = "dry_run"
                with self._lock:
                    if self._logged.get(key) == mark:
                        continue
                    self._logged[key] = mark
                d = self._new_decision(
                    v, action, governors, now,
                    outcome=OUTCOME_DRY_RUN, dry_run=True,
                )
                self._record(d)
                recorded.append(d)
                logger.warning(
                    "remediation DRY RUN: would %s node %d (%s) for "
                    "%s — %s",
                    action, v.node_id, v.host, v.detector, v.message,
                )
                continue
            d = self._new_decision(v, action, governors, now)
            ok = self._execute(d)
            if ok:
                d.outcome = OUTCOME_ACTED
                d.probation_deadline = now + self._cfg("probation_s")
                self.health.stamp_action(key, now)
                with self._lock:
                    self._window.append(now)
                    self._window = [
                        t for t in self._window
                        if now - t < self._cfg("blast_window_s")
                    ]
                    self._probation[d.decision_id] = d
                    self._logged[key] = "acted"
            else:
                d.outcome = OUTCOME_FAILED
                # Rate-limit the retry like any acted decision: stamp
                # the shared cooldown and mark the episode, so a
                # persistently-failing action (cluster API down)
                # backs off instead of re-firing — and re-recording a
                # decision + brain row + metric — every single tick.
                self.health.stamp_action(key, now)
                with self._lock:
                    self._logged[key] = "failed"
            self._record(d)
            recorded.append(d)
        return recorded

    def _new_decision(
        self,
        v: HealthVerdict,
        action: str,
        governors: Dict[str, str],
        now: float,
        outcome: str = OUTCOME_PENDING,
        dry_run: bool = False,
    ) -> RemediationDecision:
        with self._lock:
            self._seq += 1
            seq = self._seq
        return RemediationDecision(
            decision_id=seq,
            detector=v.detector,
            severity=v.severity,
            node_id=v.node_id,
            host=v.host,
            action=action,
            trigger=v.message,
            governors=governors,
            outcome=outcome,
            dry_run=dry_run,
            baseline_step_s=float(
                v.metrics.get("baseline_mean_s", 0.0)
            ),
            timestamp=now,
            trace_id=_trace.new_trace_id(),
            span_id=_trace.new_span_id(),
        )

    def _tspan(
        self,
        d: RemediationDecision,
        name: str,
        start: float,
        dur: float = 0.0,
        span_id: str = "",
        parent: Optional[str] = None,
        **tags,
    ) -> None:
        """One span of the decision's trace (no-op without a store).
        Default parent is the decision's root span."""
        if self.traces is None or not d.trace_id:
            return
        self.traces.add_span(
            d.trace_id,
            name,
            start,
            dur_s=max(dur, 0.0),
            span_id=span_id,
            parent_span_id=d.span_id if parent is None else parent,
            node_id=d.node_id,
            decision_id=d.decision_id,
            **tags,
        )

    def _execute(self, d: RemediationDecision) -> bool:
        try:
            if d.action == ACTION_RESTART_TRAINING:
                return self._exec_restart(d)
            if d.action == ACTION_CORDON_REPLACE:
                return self._exec_cordon_replace(d)
            if d.action == ACTION_SHRINK:
                return self._exec_shrink(d)
            if d.action == ACTION_DRAIN_REPLICA:
                return self._exec_drain_replica(d)
        except Exception:  # noqa: BLE001 — a failed action is an
            # outcome to record, never an engine crash
            logger.warning(
                "remediation action %s on node %d failed",
                d.action, d.node_id, exc_info=True,
            )
        return False

    def _dedupe_key(self, d: RemediationDecision, what: str) -> str:
        return f"remediation:{d.decision_id}:{what}"

    def _exec_restart(self, d: RemediationDecision) -> bool:
        self.servicer.push_action(
            d.node_id,
            EventAction.RESTART_TRAINING.value,
            dedupe_key=self._dedupe_key(d, "restart"),
        )
        return True

    def _exec_drain_replica(self, d: RemediationDecision) -> bool:
        """Serving ladder rung 0: the router stops dispatching to the
        replica and requeues everything it holds — the requests are
        safe within this one decision, whatever happens to the
        replica. The node itself is untouched (a recovered replica
        re-registers ready)."""
        if self.serving is None:
            return False
        # The drain's requeues join this decision's trace: the router
        # records a serve.requeue span per rescued request under the
        # decision root, so verdict -> drain -> requeue reads as one
        # causal chain. link= only when a trace store is wired —
        # duck-typed routers without the kwarg stay supported.
        if self.traces is not None and d.trace_id:
            self.serving.drain_replica(
                d.node_id,
                reason=d.detector,
                link=(d.trace_id, d.span_id),
            )
        else:
            self.serving.drain_replica(d.node_id, reason=d.detector)
        obs.event(
            "remediation.drain_replica",
            node_id=d.node_id, detector=d.detector,
            trace_id=d.trace_id, parent_span_id=d.span_id,
        )
        return True

    def _exec_cordon_replace(self, d: RemediationDecision) -> bool:
        node = self.job_manager.get_node(d.node_id)
        if node is None or not node.is_alive():
            return False
        if node.type == NodeType.REPLICA:
            return self._exec_replace_replica(d, node)
        if not self.job_manager.cordon_node(d.node_id, reason=d.detector):
            return False
        # From here on the node IS cordoned: every further step is
        # best-effort, and the engine must end up owning the cordon
        # record either way — a partial failure without a probation
        # would strand the pod parked forever with nothing to ever
        # roll it back or retire it.
        try:
            # Park the sick trainer (it keeps heartbeating; rollback
            # can un-cordon it) and pull it out of the next
            # rendezvous; survivors re-rendezvous without it.
            self.servicer.push_action(
                d.node_id,
                EventAction.CORDON.value,
                dedupe_key=self._dedupe_key(d, "cordon"),
            )
            for rdzv in self.rdzv_managers:
                rdzv.remove_alive_node(node.id, node_rank=node.rank)
            self.servicer.restart_peers(
                node.id, dedupe_prefix=self._dedupe_key(d, "peers")
            )
            # Purge the benched host's telemetry (same contract as a
            # departed host): its trainer is parked, so the stale
            # slow window — fleet series AND the speed monitor's
            # frozen step-time EWMA — would otherwise pin the verdict
            # active past any probation and guarantee a wrong
            # rollback. The convicting evidence already rides the
            # verdict and this decision record.
            if self.fleet is not None:
                self.fleet.remove_node(node.id)
            if self.speed_monitor is not None:
                self.speed_monitor.remove_running_node(node.id)
        except Exception:  # noqa: BLE001
            logger.warning(
                "cordon side-effects for node %d partially failed",
                d.node_id, exc_info=True,
            )
        repl = None
        try:
            repl = self.job_manager.launch_replacement(
                node, reason=d.detector
            )
        except Exception:  # noqa: BLE001 — a failed launch is NOT a
            # failed cordon: probation still governs the benched
            # node, and a failed probation rolls the cordon back.
            logger.warning(
                "replacement launch for cordoned node %d failed",
                d.node_id, exc_info=True,
            )
        d.replacement_id = repl.id if repl is not None else -1
        with self._lock:
            self._cordoned[d.node_id] = {
                "host": d.host,
                "detector": d.detector,
                "decision_id": d.decision_id,
                "replacement_id": d.replacement_id,
                "since": d.timestamp,
            }
        _CORDONED_NODES.set(len(self._cordoned))
        obs.event(
            "remediation.cordon",
            node_id=d.node_id, host=d.host, detector=d.detector,
            replacement_id=d.replacement_id,
        )
        return True

    def _exec_replace_replica(
        self, d: RemediationDecision, node
    ) -> bool:
        """Serving ladder rung 2: cordon the sick replica node (its
        fresh incarnations stay benched), drain any requests it
        re-acquired, and launch a replacement replica node through
        the ScalePlan seam. Deliberately does NOT touch the training
        world: no rendezvous removal, no peer restarts, no fleet
        telemetry purge — a sick replica must never bounce the
        trainers sharing the control plane."""
        if not self.job_manager.cordon_node(
            d.node_id, reason=d.detector
        ):
            return False
        if self.serving is not None:
            try:
                self.serving.drain_replica(
                    d.node_id, reason=d.detector
                )
            except Exception:  # noqa: BLE001
                logger.warning(
                    "drain during replica replace failed",
                    exc_info=True,
                )
        repl = None
        try:
            repl = self.job_manager.launch_replacement(
                node,
                reason=d.detector,
                node_id=self._free_replica_node_id(),
            )
        except Exception:  # noqa: BLE001 — same contract as the
            # worker path: a failed launch is governed by probation
            logger.warning(
                "replacement launch for replica %d failed",
                d.node_id, exc_info=True,
            )
        d.replacement_id = repl.id if repl is not None else -1
        with self._lock:
            self._cordoned[d.node_id] = {
                "host": d.host,
                "detector": d.detector,
                "decision_id": d.decision_id,
                "replacement_id": d.replacement_id,
                "since": d.timestamp,
            }
        _CORDONED_NODES.set(len(self._cordoned))
        # The replacement inherits the sick node's role labels
        # (launch_replacement copies node.labels), so a replaced
        # prefill replica comes back a prefill replica; record the
        # role on the decision for the audit trail.
        role = getattr(node, "labels", {}).get("serving_role", "")
        if not role and self.serving is not None:
            role_of = getattr(self.serving, "role_of", None)
            role = role_of(d.node_id) if role_of else ""
        obs.event(
            "remediation.cordon",
            node_id=d.node_id, host=d.host, detector=d.detector,
            replacement_id=d.replacement_id, replica=True,
            **({"role": role} if role else {}),
        )
        return True

    def _free_replica_node_id(self) -> int:
        """The lowest replica-namespaced node id with no LIVE node —
        the same lowest-free-index policy ``ensure_role`` uses for
        this namespace, so cordon-replace and autoscale share one
        id-allocation scheme. Replica workers register under
        base+index (constants.replica_node_id); a replacement
        launched under a plain worker-sequence id could never be
        claimed by the arriving process and would sit PENDING
        forever."""
        from dlrover_tpu.common.constants import replica_node_id

        idx = 0
        while True:
            node = self.job_manager.get_node(replica_node_id(idx))
            if node is None or not node.is_alive():
                return replica_node_id(idx)
            idx += 1

    def _exec_shrink(self, d: RemediationDecision) -> bool:
        node = self.job_manager.get_node(d.node_id)
        if node is None or not node.is_alive():
            return False
        obs.event(
            "remediation.shrink",
            node_id=d.node_id, host=d.host, detector=d.detector,
        )
        # retire_node removes the pod and fires the DELETED listener:
        # rendezvous removal + peer restarts happen there, and the
        # world re-forms >= min_nodes at the next boundary.
        self.job_manager.retire_node(d.node_id)
        if self.auto_scaler is not None:
            # The shrink must STICK: an auto-scaler still chasing the
            # old worker target would count the deficit and launch a
            # replacement on its next pass, undoing the shrink.
            # (JobMaster wires no worker auto-scaler today — any
            # composer pairing AllreduceAutoScaler with this engine
            # must pass it as the `auto_scaler` collaborator.)
            self.auto_scaler.target_workers = max(
                self.min_nodes, self.auto_scaler.target_workers - 1
            )
        return True

    # -- probation ---------------------------------------------------------

    def _throughput_recovered(self, d: RemediationDecision) -> bool:
        """Throughput back within ``recover_ratio`` of the verdict's
        own healthy baseline. Falls back to True when the engine has
        no comparable series (verdict resolution then decides)."""
        if d.baseline_step_s <= 0:
            return True
        ratio = self._cfg("recover_ratio")
        if d.action == ACTION_RESTART_TRAINING and self.store is not None:
            stats = self.store.query(
                "host.step_time", 120.0, host=d.host
            )
            if stats is not None and stats.count > 0:
                return stats.mean <= d.baseline_step_s * ratio
            return True
        if self.fleet is not None:
            # The cordoned host's series is stale/purged: judge the
            # fleet median (robust to one lingering stale entry).
            try:
                agg = self.fleet.aggregates().get("step_time_s", {})
            except Exception:  # noqa: BLE001
                return True
            p50 = agg.get("p50")
            if p50 is not None:
                return p50 <= d.baseline_step_s * ratio
        return True

    def _replacement_ok(self, d: RemediationDecision) -> bool:
        """A cordon-replace may only succeed with its replacement
        actually alive. Without this, a failed launch looks RECOVERED:
        the cordon purged the sick host's telemetry, so its verdict
        resolves and the (shrunken) fleet reads healthy — and success
        would then retire the benched pod, leaving the job
        permanently a worker short. Forcing failure instead rolls the
        cordon back and restores capacity."""
        if d.action != ACTION_CORDON_REPLACE:
            return True
        if d.replacement_id < 0:
            return False
        # RUNNING, not merely alive: PENDING counts as alive, but an
        # unschedulable replacement that never registers must not let
        # probation retire the benched pod on the strength of a fleet
        # that reads healthy only because the sick host was purged.
        repl = self.job_manager.get_node(d.replacement_id)
        return repl is not None and repl.status == NodeStatus.RUNNING

    def _review_probations(
        self, now: float, crit_subjects: Set[Tuple[str, int]]
    ) -> List[RemediationDecision]:
        finalized: List[RemediationDecision] = []
        with self._lock:
            probations = list(self._probation.values())
        for d in probations:
            subject_sick = (
                d.subject() in crit_subjects
                or any(n == d.node_id for _, n in crit_subjects)
            )
            if (
                not subject_sick
                and self._replacement_ok(d)
                and self._throughput_recovered(d)
            ):
                d.healthy_ticks += 1
            else:
                d.healthy_ticks = 0
            if d.healthy_ticks >= int(self._cfg("recovery_ticks")):
                self._finalize_success(d, now)
                finalized.append(d)
            elif now >= d.probation_deadline:
                self._finalize_failure(d, now)
                finalized.append(d)
        return finalized

    def _finalize_success(
        self, d: RemediationDecision, now: float
    ) -> None:
        with self._lock:
            # Outcome + probation removal flip atomically w.r.t. the
            # journal thread's to_snapshot (same lock): a snapshot
            # never records a RECOVERED decision still in probation.
            d.outcome = OUTCOME_RECOVERED
            d.resolved_at = now
            self._probation.pop(d.decision_id, None)
            self._ladder.pop(d.subject(), None)
            self._logged.pop(
                (d.detector, d.host, d.node_id), None
            )
            rec = (
                self._cordoned.pop(d.node_id, None)
                if d.action == ACTION_CORDON_REPLACE
                else None
            )
        if rec is not None:
            # The replacement took over and the fleet recovered:
            # complete cordon-THEN-REPLACE by retiring the sick pod.
            # Retire FIRST (the DELETED listener sees the cordon and
            # skips the fleet bounce), then clear the flag so a
            # future incarnation of this node id starts un-benched.
            self.job_manager.retire_node(d.node_id)
            self.job_manager.uncordon_node(d.node_id)
        _CORDONED_NODES.set(len(self._cordoned))
        # The derived SLO surface: how long this decision took from
        # conviction to verified recovery.
        _RECOVERY_SECONDS.set(max(now - d.timestamp, 0.0))
        obs.event(
            "remediation.recovered",
            node_id=d.node_id, host=d.host, detector=d.detector,
            action=d.action, decision_id=d.decision_id,
            trace_id=d.trace_id, parent_span_id=d.span_id,
        )
        logger.info(
            "remediation recovered: %s on node %d (%s) for %s",
            d.action, d.node_id, d.host, d.detector,
        )
        self._record(d, created=False)

    def _finalize_failure(
        self, d: RemediationDecision, now: float
    ) -> None:
        d.resolved_at = now
        subject = d.subject()
        # Mutate ALL engine state under the lock BEFORE any side
        # effect: the journal thread snapshots concurrently, and a
        # snapshot taken mid-rollback must never record a finalized
        # decision still listed under probation — a warm restore
        # would re-enter it and re-run the rollback's side effects
        # (spurious trainer bounce) on a live node.
        with self._lock:
            if d.detector == "replica_unhealthy":
                # Serving ladder: the failed rung's successor. A
                # failed replace ends at alert-only (rollback still
                # un-cordons below for the replace rung).
                try:
                    rung = SERVING_LADDER.index(d.action) + 1
                except ValueError:
                    rung = RUNG_ALERT_ONLY
                self._ladder[subject] = min(rung, RUNG_ALERT_ONLY)
                if d.action == ACTION_CORDON_REPLACE:
                    d.outcome = OUTCOME_ROLLED_BACK
                    d.note = (
                        "replica replace probation failed; rolled "
                        "back (un-cordoned), alert-only"
                    )
                else:
                    d.outcome = OUTCOME_ESCALATED
                    d.note = (
                        f"probation failed after {d.action}; "
                        "escalating to "
                        f"{SERVING_LADDER[self._ladder[subject]]}"
                    )
            elif d.action == ACTION_RESTART_TRAINING:
                # The bounce did not help: escalate to cordon-replace
                # the next time the subject clears hysteresis again.
                d.outcome = OUTCOME_ESCALATED
                d.note = (
                    "probation failed; escalating to cordon_replace"
                )
                self._ladder[subject] = RUNG_CORDON
            elif d.action == ACTION_CORDON_REPLACE:
                # The host was not the problem: roll back (un-cordon,
                # take the replacement back out) and mark the subject
                # past budget — the next conviction shrinks instead.
                d.outcome = OUTCOME_ROLLED_BACK
                d.note = "probation failed; rolled back (un-cordoned)"
                self._ladder[subject] = RUNG_SHRINK
            else:  # shrink — nothing to roll back; stop acting on it
                d.outcome = OUTCOME_ESCALATED
                d.note = "probation failed after shrink; alert-only"
                self._ladder[subject] = RUNG_ALERT_ONLY
            self._probation.pop(d.decision_id, None)
            self._logged.pop(
                (d.detector, d.host, d.node_id), None
            )
        if d.outcome == OUTCOME_ROLLED_BACK:
            self._rollback_cordon(d)
        obs.event(
            "remediation.probation_failed",
            node_id=d.node_id, host=d.host, detector=d.detector,
            action=d.action, outcome=d.outcome,
            decision_id=d.decision_id,
        )
        logger.warning(
            "remediation probation FAILED: %s on node %d (%s) for "
            "%s -> %s",
            d.action, d.node_id, d.host, d.detector, d.outcome,
        )
        self._record(d, created=False)

    def _rollback_cordon(self, d: RemediationDecision) -> None:
        with self._lock:
            rec = self._cordoned.pop(d.node_id, None)
        _CORDONED_NODES.set(len(self._cordoned))
        repl_id = rec.get("replacement_id", -1) if rec else -1
        node = self.job_manager.get_node(d.node_id)
        self.job_manager.uncordon_node(d.node_id)
        if node is None or not node.is_alive():
            # The benched pod died during probation: there is nothing
            # to roll back INTO the world. Keep the live replacement —
            # it IS the job's capacity now; retiring it too would
            # leave the world a worker short with nothing refilling
            # the deficit.
            obs.event(
                "remediation.rollback",
                node_id=d.node_id, host=d.host,
                replacement_id=repl_id, decision_id=d.decision_id,
                replacement_kept=True,
            )
            return
        if node.type != NodeType.REPLICA:
            # Serving replicas were never rendezvous members or step
            # reporters: un-cordoning one must not inject it into the
            # TRAINING world.
            for rdzv in self.rdzv_managers:
                rdzv.add_alive_node(d.node_id)
            if self.speed_monitor is not None:
                # The host is back in the world: resume its step
                # accounting (the EWMA restarts clean, so the old
                # slow window cannot instantly re-convict it).
                self.speed_monitor.add_running_node(d.node_id)
        # Un-park the trainer: restart_training doubles as un-cordon
        # on the agent side (it clears the cordon flag and rejoins at
        # the next rendezvous).
        self.servicer.push_action(
            d.node_id,
            EventAction.RESTART_TRAINING.value,
            dedupe_key=self._dedupe_key(d, "uncordon"),
        )
        if repl_id >= 0:
            repl = self.job_manager.get_node(repl_id)
            if repl is not None and repl.is_alive():
                self.job_manager.retire_node(repl_id)
        obs.event(
            "remediation.rollback",
            node_id=d.node_id, host=d.host,
            replacement_id=repl_id, decision_id=d.decision_id,
        )

    # -- recording ---------------------------------------------------------

    def _record(
        self, d: RemediationDecision, created: bool = True
    ) -> None:
        if created:
            with self._lock:
                self._decisions.append(d)
            # The decision's trace opens: root span, the convicting
            # verdict, the governor gate results, and the action with
            # its immediate outcome (acted / blocked / dry_run /
            # failed).
            self._tspan(
                d, "remediation.decision", d.timestamp,
                span_id=d.span_id, parent="",
                detector=d.detector, host=d.host,
                action=d.action, outcome=d.outcome,
            )
            self._tspan(
                d, "remediation.verdict", d.timestamp,
                detector=d.detector, severity=d.severity,
                trigger=d.trigger,
            )
            self._tspan(
                d, "remediation.governors", d.timestamp,
                **{
                    f"governor_{name}": why
                    for name, why in d.governors.items()
                },
            )
            if d.action:
                self._tspan(
                    d, f"remediation.{d.action}", d.timestamp,
                    outcome=d.outcome, dry_run=d.dry_run,
                )
        else:
            # Finalization: the probation interval and its outcome.
            end = d.resolved_at or self.clock()
            self._tspan(
                d, "remediation.probation", d.timestamp,
                dur=end - d.timestamp,
                outcome=d.outcome,
            )
            self._tspan(
                d, "remediation.outcome", end,
                outcome=d.outcome, note=d.note,
            )
        _DECISIONS_TOTAL.inc(
            detector=d.detector, action=d.action, outcome=d.outcome
        )
        obs.event(
            "remediation.decision",
            decision_id=d.decision_id, detector=d.detector,
            node_id=d.node_id, host=d.host, action=d.action,
            outcome=d.outcome, dry_run=d.dry_run,
            trace_id=d.trace_id, parent_span_id=d.span_id,
        )
        self._persist(d)

    def _persist(self, d: RemediationDecision) -> None:
        """Ship the decision to the brain datastore (best-effort by
        contract): the same channel the health plane persists verdicts
        into, so the policy history is queryable across masters."""
        if self.brain is None:
            return
        persist = getattr(
            self.brain, "persist_remediation_decision", None
        )
        if persist is None:
            return
        try:
            persist(
                job_name=self.job_name,
                decision_id=d.decision_id,
                detector=d.detector,
                node_id=d.node_id,
                host=d.host,
                action=d.action,
                outcome=d.outcome,
                dry_run=int(d.dry_run),
                governors=json.dumps(d.governors, sort_keys=True),
                message=d.trigger,
                timestamp=d.resolved_at or d.timestamp,
            )
        except Exception:  # noqa: BLE001 — a broken datastore must
            # not take remediation down
            logger.warning(
                "brain persistence of remediation decision failed",
                exc_info=True,
            )

    # -- read surface ------------------------------------------------------

    def decisions(self, limit: int = 0) -> List[RemediationDecision]:
        with self._lock:
            items = list(self._decisions)
        return items[-limit:] if limit > 0 else items

    def cordoned_nodes(self) -> List[int]:
        with self._lock:
            return sorted(self._cordoned)

    def probation_failing(self) -> bool:
        """True when remediation is demonstrably NOT helping: an
        active probation is past its deadline without recovery, or a
        finalized failure's subject is still convicted. The
        ``obs_report --health`` probe exits 1 on this."""
        now = self.clock()
        crit_subjects = {
            (v.host, v.node_id)
            for v in self.health.active_verdicts()
            if v.severity == SEVERITY_CRITICAL
        }
        with self._lock:
            for d in self._probation.values():
                if now >= d.probation_deadline:
                    return True
            for d in self._decisions:
                if (
                    d.outcome in (OUTCOME_ROLLED_BACK, OUTCOME_ESCALATED)
                    and d.subject() in crit_subjects
                ):
                    return True
        return False

    def snapshot(self) -> dict:
        """JSON payload for tools (the RPC response's dict shape)."""
        return {
            "enabled": self.enabled,
            "dry_run": self.dry_run,
            "cordoned": self.cordoned_nodes(),
            "probation_failing": self.probation_failing(),
            "decisions": [d.to_dict() for d in self.decisions()],
        }

    def query_response(self, node_id: int = -1, limit: int = 0):
        from dlrover_tpu.common import messages as msg

        decisions = [
            d
            for d in self.decisions()
            if node_id < 0 or d.node_id == node_id
        ]
        if limit > 0:
            decisions = decisions[-limit:]
        return msg.RemediationQueryResponse(
            enabled=self.enabled,
            dry_run=self.dry_run,
            cordoned=self.cordoned_nodes(),
            probation_failing=self.probation_failing(),
            decisions=[
                msg.RemediationDecisionMsg(
                    decision_id=d.decision_id,
                    detector=d.detector,
                    severity=d.severity,
                    node_id=d.node_id,
                    host=d.host,
                    action=d.action,
                    outcome=d.outcome,
                    dry_run=d.dry_run,
                    governors=dict(d.governors),
                    trigger=d.trigger,
                    timestamp=d.timestamp,
                    probation_deadline=d.probation_deadline,
                    note=d.note,
                    trace_id=d.trace_id,
                )
                for d in decisions
            ],
        )

    # -- warm-restart snapshot ---------------------------------------------

    def to_snapshot(self) -> dict:
        """JSON-safe recoverable state: the decision history, cordons,
        probations, escalation ladder, and blast-window stamps — all
        wall-clock based, so cooldowns and probation deadlines keep
        their meaning across a master restart. Hysteresis tick counts
        are deliberately NOT persisted: a fresh master re-earns the
        consecutive-sick evidence before acting (conservative)."""
        with self._lock:
            return {
                "seq": self._seq,
                "decisions": [d.to_dict() for d in self._decisions],
                # Probations serialize FULLY, not by id: the bounded
                # history deque can evict an acted decision while its
                # probation is still open (mass-degradation storms),
                # and a restore that cannot resolve the id would
                # silently drop the probation — stranding the
                # cordoned node with nothing to ever roll it back.
                "probations": [
                    d.to_dict() for d in self._probation.values()
                ],
                "cordoned": {
                    str(k): dict(v) for k, v in self._cordoned.items()
                },
                "ladder": [
                    [host, node_id, rung]
                    for (host, node_id), rung in self._ladder.items()
                ],
                "window": list(self._window),
            }

    def restore_snapshot(self, state: dict) -> None:
        with self._lock:
            self._seq = int(state.get("seq", 0))
            self._decisions.clear()
            by_id: Dict[int, RemediationDecision] = {}
            for d in state.get("decisions", []):
                dec = RemediationDecision.from_dict(d)
                self._decisions.append(dec)
                by_id[dec.decision_id] = dec
            self._probation = {}
            # Healthy-tick streaks restart (the new master must
            # re-observe M healthy ticks itself) — so the deadline
            # must leave room for them: a restart that consumed most
            # of the window would otherwise hit the deadline before
            # recovery_ticks could possibly accrue and roll back a
            # genuinely-recovered remediation. One extra interval of
            # slack: the first tick races the health monitor's first
            # re-evaluate and may still see the journaled (stale)
            # verdict as active.
            grace = (self._cfg("recovery_ticks") + 1) * self.interval
            floor = self.clock() + grace

            def _re_arm(dec: RemediationDecision) -> None:
                dec.healthy_ticks = 0
                dec.probation_deadline = max(
                    dec.probation_deadline, floor
                )
                self._probation[dec.decision_id] = dec

            for pd in state.get("probations", []):
                dec = RemediationDecision.from_dict(pd)
                # Prefer the history's object so decisions() and the
                # probation share one record (outcome updates in both).
                _re_arm(by_id.get(dec.decision_id, dec))
            for pid in state.get("probation_ids", []):  # legacy journals
                dec = by_id.get(int(pid))
                if dec is not None and dec.decision_id not in self._probation:
                    _re_arm(dec)
            self._cordoned = {
                int(k): dict(v)
                for k, v in state.get("cordoned", {}).items()
            }
            self._ladder = {
                (str(host), int(node_id)): int(rung)
                for host, node_id, rung in state.get("ladder", [])
            }
            # Legacy journals carried alert-only as a parallel set;
            # it folds into the terminal ladder rung.
            for host, node_id in state.get("alert_only", []):
                self._ladder[(str(host), int(node_id))] = (
                    RUNG_ALERT_ONLY
                )
            self._window = [
                float(t) for t in state.get("window", [])
            ]
            self._sick = {}
            self._logged = {}
        _CORDONED_NODES.set(len(self._cordoned))
        _PROBATIONS_ACTIVE.set(len(self._probation))


def render_remediation(payload: dict) -> str:
    """Human rendering of a remediation snapshot (``RemediationEngine.
    snapshot()`` or the assembled ``RemediationQueryResponse``) — the
    remediation section of ``obs_report --health``."""
    decisions = list(payload.get("decisions", []))
    cordoned = list(payload.get("cordoned", []))
    mode = "DRY RUN" if payload.get("dry_run") else "active"
    if not payload.get("enabled", True):
        mode = "disabled"
    lines = [
        f"remediation ({mode}): {len(decisions)} decision"
        f"{'' if len(decisions) == 1 else 's'}, "
        f"{len(cordoned)} node(s) cordoned"
        + (f" {cordoned}" if cordoned else "")
    ]
    if payload.get("probation_failing"):
        lines.append(
            "  PROBATION FAILING: an action did not restore health"
        )
    for d in decisions[-10:]:
        subject = d.get("host") or f"node {d.get('node_id')}"
        lines.append(
            f"  #{d.get('decision_id')} "
            f"[{d.get('outcome', '?'):<11}] "
            f"{d.get('detector', '?')} ({subject}) -> "
            f"{d.get('action', '?')}"
            + (" [dry-run]" if d.get("dry_run") else "")
        )
        governors = d.get("governors") or {}
        vetoes = {
            k: v for k, v in governors.items() if v != GOVERNOR_OK
        }
        if vetoes:
            for name, why in sorted(vetoes.items()):
                lines.append(f"      governor {name}: {why}")
        elif governors:
            lines.append(
                "      governors ok: "
                + ", ".join(sorted(governors))
            )
        note = d.get("note") or ""
        if note:
            lines.append(f"      {note}")
    return "\n".join(lines)
