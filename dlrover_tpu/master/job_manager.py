"""Job/node manager on the master.

Capability parity with the reference's node management layer
(dlrover/python/master/node/dist_job_manager.py:87): tracks every node's
state machine, classifies failures, decides relaunches, and feeds the
rendezvous managers / task manager / speed monitor. Platform-specific
scaling (GKE TPU pod-slices, Ray) plugs in via a ``Scaler`` interface;
the local platform simply records intents so tests can assert on them.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.constants import (
    JobExitReason,
    NodeAction,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeResource

logger = get_logger("job_manager")

_NODE_EVENTS = obs.counter(
    "dlrover_node_events_total",
    "Node lifecycle events observed by the master",
    ("event",),
)
_RELAUNCHES = obs.counter(
    "dlrover_node_relaunch_total",
    "Node relaunches ordered by the master",
    ("reason",),
)


class ScalePlan:
    """Target state the scaler should realize."""

    def __init__(self):
        self.launch_nodes: List[Node] = []
        self.remove_nodes: List[Node] = []

    def empty(self) -> bool:
        return not self.launch_nodes and not self.remove_nodes

    def __repr__(self):
        return (
            f"ScalePlan(launch={[n.id for n in self.launch_nodes]}, "
            f"remove={[n.id for n in self.remove_nodes]})"
        )


class Scaler:
    """Executes ScalePlans. Subclasses talk to GKE/Ray; the base class
    records plans for local mode and tests."""

    def __init__(self):
        self.executed_plans: List[ScalePlan] = []

    def scale(self, plan: ScalePlan) -> None:
        self.executed_plans.append(plan)


@dataclasses.dataclass(frozen=True)
class RolePolicy:
    """Per-role lifecycle policy the master applies at registration.

    ``critical``: losing such a node past its relaunch budget fails
    the whole job instead of elastically shrinking it (ref:
    chief/evaluator/PS are always critical, workers per the
    critical-nodes spec, master/node/training_node.py:40-72).
    ``max_relaunch``: role-specific relaunch-budget override; None
    keeps the job-wide default.
    """

    critical: bool = False
    max_relaunch: Optional[int] = None


def default_role_policies() -> Dict[str, RolePolicy]:
    return {
        NodeType.CHIEF: RolePolicy(critical=True),
        NodeType.EVALUATOR: RolePolicy(critical=True),
        NodeType.EMBEDDING: RolePolicy(critical=True),
    }


def parse_critical_workers(spec: str) -> Dict[int, Optional[int]]:
    """Parse the critical-workers spec into {rank: relaunch budget}.

    ``""`` / ``"none"`` -> no critical workers; ``"all"`` -> every
    worker critical (budget None = keep default); ``"0:3,5:1"`` ->
    those ranks critical with the given per-rank relaunch budgets.
    (ref: training_node.py:81 get_critical_worker_index)
    """
    spec = (spec or "").strip().lower()
    if spec in ("", "none"):
        return {}
    if spec == "all":
        return {-1: None}  # sentinel: every rank
    out: Dict[int, Optional[int]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        rank_s, _, budget_s = part.partition(":")
        try:
            rank = int(rank_s)
            budget = int(budget_s) if budget_s else None
        except ValueError:
            raise ValueError(
                f"bad critical-workers entry {part!r}: expected "
                "'rank' or 'rank:relaunch_budget' (or 'all'/'none')"
            ) from None
        if rank < 0 or (budget is not None and budget < 0):
            raise ValueError(
                f"bad critical-workers entry {part!r}: rank and "
                "budget must be non-negative"
            )
        out[rank] = budget
    return out


class JobManager:
    """Tracks nodes and drives relaunch decisions."""

    def __init__(
        self,
        scaler: Optional[Scaler] = None,
        max_relaunch: int = 3,
        heartbeat_timeout: float = 180.0,
        pending_timeout: Optional[float] = None,
        role_policies: Optional[Dict[str, RolePolicy]] = None,
        critical_workers: str = "",
        monitor_interval: float = 30.0,
    ):
        from dlrover_tpu.common.config import Context

        self._lock = threading.Lock()
        self._nodes: Dict[int, Node] = {}
        self._scaler = scaler or Scaler()
        self._max_relaunch = max_relaunch
        self._heartbeat_timeout = heartbeat_timeout
        self._pending_timeout = (
            Context.singleton().pending_timeout_secs
            if pending_timeout is None else pending_timeout
        )
        self._next_node_id = 0
        self._monitor_interval = monitor_interval
        self._stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        # subscribers: fn(node, event_type)
        self._listeners: List[Callable[[Node, str], None]] = []
        self._role_policies = (
            default_role_policies()
            if role_policies is None
            else dict(role_policies)
        )
        self._critical_workers = parse_critical_workers(critical_workers)
        # Set when a critical node is lost for good: (reason, detail).
        self._job_failure: Optional[tuple] = None
        # Multi-job pool grant: when this job runs under a pool
        # master, the pool caps its ALIVE node count here (None =
        # single-job, unlimited). ensure_role respects it (the
        # serving plane's autoscale seam), and the remediation
        # engine's pool_grant governor consults grant_headroom()
        # before launching replacements — per-job planes become
        # consumers of pool grants instead of assuming an infinite
        # cluster.
        self.pool_grant: Optional[int] = None

    @property
    def scaler(self) -> Scaler:
        return self._scaler

    # -- membership ---------------------------------------------------------

    def adopt_node(self, node: Node) -> None:
        """Track a node created by the auto-scaler (it will register
        itself over RPC once its agent starts)."""
        with self._lock:
            self._nodes[node.id] = node
            # Keep the id allocator ahead of externally-minted ids:
            # launch_replacement must never collide with (and silently
            # overwrite) an in-flight auto-scaler node.
            self._next_node_id = max(self._next_node_id, node.id + 1)

    def add_listener(self, fn: Callable[[Node, str], None]) -> None:
        self._listeners.append(fn)

    def _notify(self, node: Node, event_type: str) -> None:
        for fn in self._listeners:
            try:
                fn(node, event_type)
            except Exception:  # noqa: BLE001
                logger.exception("node event listener failed")

    def register_node(
        self,
        node_type: str = NodeType.WORKER,
        node_id: Optional[int] = None,
        rank: int = -1,
        addr: str = "",
        resource: Optional[NodeResource] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> Node:
        """Called when an agent announces itself (or a pod is created)."""
        with self._lock:
            if node_id is None:
                node_id = self._next_node_id
            # Only worker-range ids advance the sequence: a
            # namespaced registration (PS 1M+, evaluator 2M+, data
            # worker 3M+, replica 4M+) must not drag the worker id
            # sequence into a role namespace — later worker-sequence
            # launches would mint ids an arriving namespaced agent
            # believes are ITS OWN and silently merge onto.
            from dlrover_tpu.common.constants import PS_NODE_ID_BASE

            if node_id < PS_NODE_ID_BASE:
                self._next_node_id = max(
                    self._next_node_id, node_id + 1
                )
            node = self._nodes.get(node_id)
            if node is not None and node.status in NodeStatus.TERMINAL:
                # A relaunched agent re-registering under its old id: the
                # old Node is a finished incarnation, start a fresh one
                # carrying over rank and the relaunch budget.
                fresh = Node(
                    type=node.type,
                    id=node.id,
                    rank=node.rank,
                    host_addr=addr or node.host_addr,
                    config_resource=node.config_resource,
                    relaunch_count=node.relaunch_count,
                    max_relaunch_count=node.max_relaunch_count,
                    critical=node.critical,
                    # The cordon outlives the incarnation: only the
                    # remediation engine un-cordons. Dropping it here
                    # would let a benched host whose agent was gone
                    # past the heartbeat timeout rejoin the world on
                    # re-register, next to its replacement.
                    cordoned=node.cordoned,
                    labels=dict(node.labels),
                )
                self._nodes[node_id] = fresh
                node = fresh
            elif node is None:
                node = Node(
                    type=node_type,
                    id=node_id,
                    rank=rank if rank >= 0 else node_id,
                    host_addr=addr,
                    config_resource=resource or NodeResource(),
                    max_relaunch_count=self._max_relaunch,
                )
                self._nodes[node_id] = node
            node.host_addr = addr or node.host_addr
            if labels:
                # The registering process's declared labels win over
                # a PENDING launch's (they describe what actually
                # arrived).
                node.labels.update(labels)
            self._apply_role_policy(node)
            node.update_status(NodeStatus.RUNNING)
            node.update_heartbeat()
        _NODE_EVENTS.inc(event="register")
        obs.event(
            "node.register",
            node_id=node.id, type=node.type, node_rank=node.rank,
        )
        self._notify(node, NodeEventType.CREATED)
        return node

    def _apply_role_policy(self, node: Node) -> None:
        """Stamp role-derived lifecycle attributes on a node. Called
        under the lock at registration; idempotent for re-registers."""
        policy = self._role_policies.get(node.type)
        if policy is not None:
            node.critical = policy.critical
            if policy.max_relaunch is not None:
                node.max_relaunch_count = policy.max_relaunch
        if node.type == NodeType.WORKER and self._critical_workers:
            if -1 in self._critical_workers:  # "all"
                budget = self._critical_workers[-1]
            elif node.rank in self._critical_workers:
                budget = self._critical_workers[node.rank]
            else:
                return
            node.critical = True
            if budget is not None:
                node.max_relaunch_count = budget

    def get_node(self, node_id: int) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def list_nodes(self, node_type: str = "") -> List[Node]:
        with self._lock:
            return [
                n
                for n in self._nodes.values()
                if not node_type or n.type == node_type
            ]

    def alive_nodes(self) -> List[Node]:
        with self._lock:
            return [n for n in self._nodes.values() if n.is_alive()]

    def alive_workers(self, include_chief: bool = False) -> List[Node]:
        """Alive, NON-cordoned training workers. The cordon exclusion
        is deliberate and the default everywhere: a benched host is
        out of the training world — it must not count toward scaling
        capacity or the elastic floor, nor receive fleet broadcasts
        (its agent overloads RESTART_TRAINING as un-cordon)."""
        types = (
            (NodeType.WORKER, NodeType.CHIEF)
            if include_chief
            else (NodeType.WORKER,)
        )
        with self._lock:
            return [
                n
                for n in self._nodes.values()
                if n.type in types and n.is_alive() and not n.cordoned
            ]

    # Beats landing on a PENDING replacement within this window after
    # the relaunch are treated as last-gasp traffic from the agent
    # being replaced and dropped; a genuinely-alive agent (e.g. the
    # failure-report response was lost and it restarted in place)
    # keeps beating past the window, so the PENDING->RUNNING recovery
    # in check_nodes_once still fires for it. 2x the agent heartbeat
    # cadence (agent.py AgentConfig.heartbeat_interval=15).
    PENDING_HEARTBEAT_GRACE = 30.0

    def update_heartbeat(self, node_id: int) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return
            # Bound by the pending timeout so a short operator-set
            # timeout can never starve the PENDING->RUNNING recovery
            # of every heartbeat before it abandons the node.
            grace = min(
                self.PENDING_HEARTBEAT_GRACE, self._pending_timeout / 2
            )
            if (
                node.status == NodeStatus.PENDING
                and time.monotonic() - node.create_time < grace
            ):
                return
            node.update_heartbeat()

    # -- failure handling ---------------------------------------------------

    def classify_exit(self, error_data: str, level: str) -> str:
        if level == TrainingExceptionLevel.NODE_ERROR:
            return NodeExitReason.HARDWARE_ERROR
        text = (error_data or "").lower()
        # error_data carries raw stderr: match the whole token "oom"
        # plus the kernel/k8s killer spellings, but NOT every token
        # merely starting with "oom" ("oom_score_adj" appears in
        # ordinary procfs dumps of unrelated crashes).
        if (
            re.search(r"\boom\b|\boomkill", text)
            or "out of memory" in text
            or "resource_exhausted" in text
        ):
            return NodeExitReason.OOM
        # A PEER's death, not this node's: jax's coordination client
        # force-aborts every surviving task when another task dies,
        # with stderr that says the LEADER "was preempted/died" —
        # that describes the other task. Classifying the survivor as
        # PREEMPTED escalated to a node relaunch and the agent
        # stopped supervising, so a coordinator-host kill took the
        # whole job down (found by the alternating-victim soak
        # drill). The surviving node is healthy: restart in place and
        # re-rendezvous into the shrunken world.
        if (
            "jax distributed service detected fatal errors" in text
            or "another task died" in text
        ):
            # Only the specific abort fingerprints: a bare
            # "coordination service" mention could ride along in the
            # stderr of a GENUINELY preempted node and must not steal
            # its RELAUNCH_NODE classification.
            return NodeExitReason.KILLED
        if re.search(r"\bpreempt", text):
            return NodeExitReason.PREEMPTED
        return NodeExitReason.KILLED

    def handle_failure_report(
        self,
        node_id: int,
        error_data: str,
        level: str,
        restart_count: int,
        fatal: bool = False,
    ) -> str:
        """Returns the :class:`NodeAction` verdict, which the servicer
        sends back so agent and master never both own the restart.

        A non-fatal PROCESS_ERROR means the agent on that node is
        restarting the training process itself — the node (pod) is
        alive, so it must stay RUNNING here (ref: process restarts are
        agent-local, the master only replaces *nodes*,
        dist_job_manager.py:489).
        """
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return NodeAction.STOP
            # Idempotency: report RPCs are retried, so a duplicate of a
            # report we already acted on must not relaunch twice or
            # fail the replacement incarnation.
            if node.status == NodeStatus.PENDING:
                return NodeAction.RELAUNCH_NODE
            if node.status in NodeStatus.TERMINAL:
                return NodeAction.STOP
            node.exit_reason = self.classify_exit(error_data, level)
            # OOM and preemption escalate to a node relaunch (OOM pods
            # get grown resources in the reference,
            # resource/local_optimizer.py:96); plain app crashes are
            # retried in place by the agent.
            if (
                not fatal
                and level == TrainingExceptionLevel.PROCESS_ERROR
                and node.exit_reason
                not in (NodeExitReason.OOM, NodeExitReason.PREEMPTED)
            ):
                node.process_failure_count = restart_count + 1
                logger.warning(
                    "node %d training process failed; agent is "
                    "restarting it (count=%d)",
                    node_id,
                    node.process_failure_count,
                )
                return NodeAction.RESTART_IN_PLACE
            if fatal:
                node.exit_reason = NodeExitReason.FATAL_ERROR
            node.update_status(NodeStatus.FAILED)
            relaunch = node.should_relaunch()
            if relaunch:
                node.inc_relaunch_count()
            else:
                self._note_critical_loss(node)
        logger.warning(
            "node %d failed (%s, level=%s, fatal=%s) relaunch=%s",
            node_id,
            node.exit_reason,
            level,
            fatal,
            relaunch,
        )
        _NODE_EVENTS.inc(event="fail")
        obs.event(
            "node.fail",
            node_id=node_id, type=node.type,
            reason=node.exit_reason or "", relaunch=relaunch,
        )
        self._notify(node, NodeEventType.MODIFIED)
        if relaunch:
            self._relaunch(node)
            return NodeAction.RELAUNCH_NODE
        return NodeAction.STOP

    def _note_critical_loss(self, node: Node) -> None:
        """A node failed for good (budget exhausted / unrelaunchable).
        For critical roles that means the job cannot make progress:
        record the job-level failure for master.run to act on. Called
        under the lock."""
        if not node.critical or self._job_failure is not None:
            return
        self._job_failure = (
            JobExitReason.CRITICAL_NODE_FAILED,
            f"critical {node.type} node {node.id} (rank {node.rank}) "
            f"lost: {node.exit_reason or 'unknown'} after "
            f"{node.relaunch_count}/{node.max_relaunch_count} relaunches",
        )
        logger.error("job failed: %s", self._job_failure[1])

    def job_failed(self) -> bool:
        with self._lock:
            return self._job_failure is not None

    @property
    def job_failure(self) -> Optional[tuple]:
        return self._job_failure

    def _relaunch(self, node: Node) -> None:
        _RELAUNCHES.inc(reason=node.exit_reason or "unknown")
        obs.event(
            "node.relaunch",
            node_id=node.id, type=node.type,
            reason=node.exit_reason or "",
            relaunch_count=node.relaunch_count,
        )
        plan = ScalePlan()
        new_node = Node(
            type=node.type,
            id=node.id,
            rank=node.rank,
            status=NodeStatus.PENDING,
            config_resource=node.config_resource,
            relaunch_count=node.relaunch_count,
            max_relaunch_count=node.max_relaunch_count,
            relaunch_reason=node.exit_reason,
            critical=node.critical,
            # The cordon outlives the incarnation (same contract as
            # register_node): a benched host whose pod died must come
            # back benched, not rejoin next to its replacement.
            cordoned=node.cordoned,
        )
        # Track the new incarnation: the failed node is being replaced,
        # so the job is NOT done (all_workers_done must see PENDING).
        with self._lock:
            self._nodes[node.id] = new_node
        plan.launch_nodes.append(new_node)
        plan.remove_nodes.append(node)
        self._scaler.scale(plan)

    def handle_node_gone(self, node_id: int, reason: str = "") -> None:
        """A cluster event (pod failed/deleted/preempted) removed the
        node out from under us — the agent may never get to report.
        (ref: _process_event on DELETED, dist_job_manager.py:401)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or node.status in NodeStatus.TERMINAL:
                return
            # The pod-Deleted event for a node we already relaunched
            # (the scaler removes the old pod as part of the plan)
            # must not fail the fresh PENDING replacement — same
            # duplicate guard as handle_failure_report.
            if node.status == NodeStatus.PENDING:
                return
            node.exit_reason = self.classify_exit(
                reason, TrainingExceptionLevel.PROCESS_ERROR
            )
            if "preempt" in (reason or "").lower():
                node.exit_reason = NodeExitReason.PREEMPTED
            node.update_status(NodeStatus.FAILED)
            relaunch = node.should_relaunch()
            if relaunch:
                node.inc_relaunch_count()
            else:
                self._note_critical_loss(node)
        logger.warning(
            "node %d gone (%s); relaunch=%s", node_id, reason, relaunch
        )
        _NODE_EVENTS.inc(event="gone")
        obs.event(
            "node.gone",
            node_id=node_id, type=node.type,
            reason=node.exit_reason or "", relaunch=relaunch,
        )
        self._notify(node, NodeEventType.DELETED)
        if relaunch:
            self._relaunch(node)

    # -- remediation seams (cordon / replace) -------------------------------

    def cordon_node(self, node_id: int, reason: str = "") -> bool:
        """Mark a live node cordoned: it stays alive (heartbeating,
        reversible) but leaves the rendezvous and stops counting
        toward the auto-scale target, so a replacement can be launched
        next to it. Returns False for unknown/dead/already-cordoned
        nodes (idempotent for replays)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.is_alive() or node.cordoned:
                return False
            node.cordoned = True
        _NODE_EVENTS.inc(event="cordon")
        obs.event(
            "node.cordon",
            node_id=node_id, type=node.type, reason=reason,
        )
        logger.warning(
            "node %d cordoned (%s): excluded from rendezvous, "
            "replacement pending", node_id, reason or "remediation",
        )
        self._notify(node, NodeEventType.MODIFIED)
        return True

    def uncordon_node(self, node_id: int) -> bool:
        """Reverse a cordon (remediation rollback): the node counts
        toward the target again and may rejoin the rendezvous."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.cordoned:
                return False
            node.cordoned = False
        _NODE_EVENTS.inc(event="uncordon")
        obs.event("node.uncordon", node_id=node_id, type=node.type)
        logger.info("node %d un-cordoned", node_id)
        self._notify(node, NodeEventType.MODIFIED)
        return True

    def launch_replacement(
        self,
        node: Node,
        reason: str = "",
        node_id: Optional[int] = None,
    ) -> Optional[Node]:
        """Launch a fresh worker (new id/rank, copied resources) to
        stand in for ``node`` via a ScalePlan — the cordon-then-
        replace half-step: the old node is NOT removed here, so a
        failed probation can roll back by retiring the replacement
        instead. Returns the PENDING replacement node.

        ``node_id`` overrides the worker-sequence id for roles whose
        agents register under NAMESPACED ids (serving replicas,
        constants.replica_node_id): the arriving process must be able
        to claim the PENDING node, which it can only do when the
        launch used the id it will register with."""
        with self._lock:
            if node_id is not None:
                new_id = node_id
                # Namespaced ids must not drag the worker sequence
                # into their namespace (same rule as register_node).
                from dlrover_tpu.common.constants import (
                    PS_NODE_ID_BASE,
                )

                if new_id < PS_NODE_ID_BASE:
                    self._next_node_id = max(
                        self._next_node_id, new_id + 1
                    )
            else:
                new_id = self._next_node_id
                self._next_node_id += 1
            resource = (
                NodeResource.from_dict(node.config_resource.to_dict())
                if node.config_resource is not None
                else NodeResource()
            )
            repl = Node(
                type=node.type,
                id=new_id,
                rank=new_id,
                status=NodeStatus.PENDING,
                config_resource=resource,
                max_relaunch_count=self._max_relaunch,
                relaunch_reason=reason,
                # The stand-in inherits the replaced node's role
                # labels: a replaced prefill replica must come back
                # a prefill replica, or the role fleet silently
                # changes shape under remediation.
                labels=dict(node.labels),
            )
            self._apply_role_policy(repl)
            # The stand-in inherits the replaced worker's criticality:
            # the rank-keyed critical_workers spec cannot see the new
            # rank, and losing the replacement past its budget must
            # fail the job exactly as losing the original would have.
            repl.critical = repl.critical or node.critical
            self._nodes[new_id] = repl
        plan = ScalePlan()
        plan.launch_nodes.append(repl)
        self._scaler.scale(plan)
        _NODE_EVENTS.inc(event="replace")
        obs.event(
            "node.replace",
            node_id=node.id, replacement_id=new_id, reason=reason,
        )
        logger.info(
            "launching replacement node %d for cordoned node %d (%s)",
            new_id, node.id, reason or "remediation",
        )
        self._notify(repl, NodeEventType.CREATED)
        return repl

    def retire_node(self, node_id: int) -> None:
        """Gracefully retire a node (drained PS, scale-in): DELETED
        through the normal transition path so listeners fire and
        finish_time is set, then the pod is removed."""
        with self._lock:
            node = self._nodes.get(node_id)
        if node is None:
            return
        node.relaunchable = False
        node.update_status(NodeStatus.DELETED)
        self._notify(node, NodeEventType.DELETED)
        plan = ScalePlan()
        plan.remove_nodes.append(node)
        self.scaler.scale(plan)

    def handle_node_succeeded(self, node_id: int) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None:
                node.update_status(NodeStatus.SUCCEEDED)
        if node is not None:
            _NODE_EVENTS.inc(event="succeeded")
            self._notify(node, NodeEventType.MODIFIED)

    # -- hang watchdog ------------------------------------------------------

    def start(self) -> None:
        if self._monitor_thread is not None:
            return
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, name="node-monitor", daemon=True
        )
        self._monitor_thread.start()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self._monitor_interval):
            self.check_nodes_once()

    def check_nodes_once(self) -> None:
        """One watchdog pass: heartbeat + pending timeouts. All
        stamps involved (create_time / heartbeat_time) are monotonic,
        set on this master — a wall-clock step cannot fire or mask a
        timeout."""
        now = time.monotonic()
        dead: List[Node] = []
        with self._lock:
            for node in self._nodes.values():
                if (
                    node.is_alive()
                    and node.heartbeat_time > 0
                    and now - node.heartbeat_time
                    > self._heartbeat_timeout
                ):
                    node.exit_reason = NodeExitReason.KILLED
                    node.update_status(NodeStatus.FAILED)
                    dead.append(node)
                elif (
                    node.status == NodeStatus.PENDING
                    and node.heartbeat_time > 0
                    and now - node.heartbeat_time
                    < self._heartbeat_timeout
                ):
                    # The node is alive and talking to us even though
                    # no status report arrived (e.g. the failure-report
                    # response was lost and the agent restarted in
                    # place): a heartbeating node is RUNNING, not a
                    # stuck replacement to abandon.
                    node.update_status(NodeStatus.RUNNING)
                    logger.info(
                        "pending node %d is heartbeating; marking "
                        "RUNNING", node.id,
                    )
                elif (
                    node.status == NodeStatus.PENDING
                    and now - node.create_time > self._pending_timeout
                ):
                    # A replacement that never came up (or a scaler
                    # that cannot launch, e.g. local mode): abandon it
                    # so all_workers_done() can complete the job
                    # (ref: seconds_to_wait_pending_pod=900).
                    node.exit_reason = JobExitReason.PENDING_TIMEOUT
                    node.relaunchable = False
                    node.update_status(NodeStatus.FAILED)
                    # Only a replacement for a previously-running node
                    # counts as a critical LOSS: an initial schedule
                    # that never materialized (e.g. a platform that
                    # cannot launch evaluators) leaves the job exactly
                    # as it was, so it must not fail a healthy run.
                    if node.relaunch_count > 0:
                        self._note_critical_loss(node)
                    logger.warning(
                        "node %d pending for >%ss; abandoning",
                        node.id,
                        self._pending_timeout,
                    )
        for node in dead:
            logger.warning(
                "node %d heartbeat timeout (>%ss); treating as dead",
                node.id,
                self._heartbeat_timeout,
            )
            _NODE_EVENTS.inc(event="heartbeat_timeout")
            obs.event(
                "node.heartbeat_timeout",
                node_id=node.id, type=node.type,
                timeout_s=self._heartbeat_timeout,
            )
            self._notify(node, NodeEventType.DELETED)
            if node.should_relaunch():
                node.inc_relaunch_count()
                self._relaunch(node)
            else:
                with self._lock:
                    self._note_critical_loss(node)

    def stop(self) -> None:
        self._stop.set()

    # -- warm-restart snapshot ----------------------------------------------

    # Node fields that are process-local clocks: meaningless (and
    # dangerous — instant heartbeat timeout) in a new master process.
    _CLOCK_FIELDS = ("create_time", "heartbeat_time")

    def to_snapshot(self) -> dict:
        """JSON-safe recoverable state: the node table (minus
        process-local monotonic clocks) + relaunch/failure facts."""
        with self._lock:
            nodes = []
            for node in self._nodes.values():
                d = node.to_dict()
                for f in self._CLOCK_FIELDS:
                    d.pop(f, None)
                # start/finish are wall stamps but carry no decisions;
                # drop them too so a restored node is visibly fresh.
                d.pop("start_time", None)
                d.pop("finish_time", None)
                nodes.append(d)
            return {
                "nodes": nodes,
                "next_node_id": self._next_node_id,
                "job_failure": (
                    list(self._job_failure)
                    if self._job_failure is not None else None
                ),
            }

    def restore_snapshot(self, state: dict) -> None:
        """Rebuild the node table from a snapshot. Clocks restart from
        'now': every restored alive node gets a fresh heartbeat stamp,
        so agents have a full heartbeat_timeout to reconnect before
        the watchdog declares them dead (the outage already cost them
        their cadence — the old stamps would kill the whole fleet on
        the first sweep)."""
        with self._lock:
            self._nodes = {}
            for d in state.get("nodes", []):
                node = Node.from_dict(d)
                if node.is_alive():
                    node.update_heartbeat()
                self._nodes[node.id] = node
            self._next_node_id = int(
                state.get("next_node_id", len(self._nodes))
            )
            failure = state.get("job_failure")
            self._job_failure = tuple(failure) if failure else None

    def all_workers_done(self) -> bool:
        """All training nodes (workers AND chiefs) reached a terminal
        state. Evaluators do not gate completion — they follow the
        training fleet and are retired by the master when it ends
        (ref: the estimator evaluator is stopped when the chief
        finishes)."""
        with self._lock:
            training = [
                n
                for n in self._nodes.values()
                if n.type in (NodeType.WORKER, NodeType.CHIEF)
            ]
            if not training:
                return False
            return all(n.status in NodeStatus.TERMINAL for n in training)

    # -- role-aware queries and scheduling ----------------------------------

    def _grant_headroom_locked(self) -> Optional[int]:
        if self.pool_grant is None:
            return None
        alive = sum(
            1 for n in self._nodes.values() if n.is_alive()
        )
        return max(self.pool_grant - alive, 0)

    def grant_headroom(self) -> Optional[int]:
        """Alive-node headroom left inside this job's pool grant
        (None = no pool, unlimited). Cordoned nodes still count:
        they hold their host until retired, so a replacement needs
        real headroom, not a benched slot."""
        with self._lock:
            return self._grant_headroom_locked()

    def is_chief_running(self) -> bool:
        """Whether any chief node is RUNNING (PS-strategy trainers wait
        for the chief to initialize shared state before stepping)."""
        with self._lock:
            return any(
                n.type == NodeType.CHIEF
                and n.status == NodeStatus.RUNNING
                for n in self._nodes.values()
            )

    def ensure_role(
        self,
        node_type: str,
        count: int,
        resource: Optional[NodeResource] = None,
        labels: Optional[Dict[str, str]] = None,
    ) -> List[Node]:
        """Schedule nodes so ``count`` of ``node_type`` are alive.

        The master's way to ask the platform for role nodes the job
        spec wants but no agent has registered yet — e.g. a standalone
        evaluator the trainer's evaluate loop will attach to. Returns
        the newly launched (PENDING) nodes; no-op if enough are alive.

        ``labels`` scopes the target to the matching label set (the
        serving plane's per-role autoscaling: prefill and decode
        replica counts are independent targets within one node type).
        Launched nodes carry the labels; alive nodes of the type with
        DIFFERENT labels neither count toward the target nor have
        their ids reused.
        """
        from dlrover_tpu.common.constants import (
            evaluator_node_id,
            ps_node_id,
            replica_node_id,
        )

        # Role-namespaced ids (same scheme the agents use on their
        # RPCs) so the arriving agent claims the PENDING node instead
        # of colliding with a worker rank.
        role_id = {
            NodeType.EVALUATOR: evaluator_node_id,
            NodeType.EMBEDDING: ps_node_id,
            NodeType.REPLICA: replica_node_id,
        }.get(node_type)

        plan = ScalePlan()
        launched: List[Node] = []
        capped = False
        with self._lock:
            headroom = self._grant_headroom_locked()

            def _matches(n: Node) -> bool:
                if n.type != node_type or not n.is_alive():
                    return False
                if labels:
                    return all(
                        n.labels.get(k) == v
                        for k, v in labels.items()
                    )
                return True

            alive = sum(
                1 for n in self._nodes.values() if _matches(n)
            )
            # The id scan must reach past indices occupied by alive
            # same-type nodes of OTHER label sets (a labeled call
            # skips them without counting them toward its target).
            same_type_alive = sum(
                1
                for n in self._nodes.values()
                if n.type == node_type and n.is_alive()
            )
            scan = count + (
                same_type_alive if role_id is not None else 0
            )
            for index in range(scan):
                if alive + len(launched) >= count:
                    break
                if headroom is not None and len(launched) >= headroom:
                    # Pool grant exhausted: scale intents beyond the
                    # grant are dropped, not queued — the caller
                    # (serving autoscaler, evaluator schedule) will
                    # re-ask when the pool grows the grant.
                    capped = True
                    break
                if role_id is not None:
                    node_id = role_id(index)
                    existing = self._nodes.get(node_id)
                    if existing is not None and existing.is_alive():
                        continue
                    rank = index
                else:
                    node_id = self._next_node_id
                    self._next_node_id += 1
                    rank = node_id
                node = Node(
                    type=node_type,
                    id=node_id,
                    rank=rank,
                    status=NodeStatus.PENDING,
                    config_resource=resource or NodeResource(),
                    max_relaunch_count=self._max_relaunch,
                    labels=dict(labels or {}),
                )
                self._apply_role_policy(node)
                self._nodes[node.id] = node
                plan.launch_nodes.append(node)
                launched.append(node)
        if capped:
            obs.event(
                "pool.grant_capped",
                role=node_type, want=count, grant=self.pool_grant,
            )
            logger.warning(
                "ensure_role(%s, %d) capped by pool grant %s "
                "(launched %d)",
                node_type, count, self.pool_grant, len(launched),
            )
        if not plan.empty():
            self._scaler.scale(plan)
        for node in launched:
            self._notify(node, NodeEventType.CREATED)
        return launched

    def retire_role(self, node_type: str) -> None:
        """Scale a whole role out (e.g. evaluators once training is
        done) through the normal retirement path."""
        for node in self.list_nodes(node_type):
            if node.is_alive():
                self.retire_node(node.id)

    def terminate_job(self) -> None:
        """Tear the whole fleet down (job-level failure): every alive
        node is retired so the platform reclaims its pods instead of
        leaving them training against a dead master."""
        for node in self.list_nodes():
            if node.is_alive():
                self.retire_node(node.id)
