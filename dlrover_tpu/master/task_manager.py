"""Dynamic data sharding: the master-side task manager.

Parity: dlrover/python/master/shard/task_manager.py:37 (TaskManager) and
batch_dataset_manager.py. Shards flow todo -> doing -> done; a shard
assigned to a worker that dies or times out goes back to todo, which is
what gives exactly-once(-ish) data consumption under elasticity without
any coordination in the training processes.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.dataset_splitter import (
    DatasetSplitter,
    Shard,
    new_dataset_splitter,
)

logger = get_logger("task_manager")


@dataclasses.dataclass
class Task:
    task_id: int
    task_type: str
    shard: Optional[Shard] = None

    @classmethod
    def wait_task(cls) -> "Task":
        return cls(task_id=-1, task_type=TaskType.WAIT)


@dataclasses.dataclass
class DoingTask:
    task: Task
    node_id: int
    start_time: float


class DatasetManager:
    """Todo/doing bookkeeping for one named dataset."""

    def __init__(self, splitter: DatasetSplitter, task_type: str):
        self.splitter = splitter
        self.task_type = task_type
        self.todo: List[Task] = []
        self.doing: Dict[int, DoingTask] = {}
        self._task_id = 0
        self._completed_step = 0

    def create_tasks(self) -> None:
        if self.splitter.epoch_finished():
            return
        self.splitter.create_shards()
        for shard in self.splitter.get_shards():
            self.todo.append(
                Task(
                    task_id=self._task_id,
                    task_type=self.task_type,
                    shard=shard,
                )
            )
            self._task_id += 1

    def get_task(self, node_id: int) -> Task:
        if not self.todo and not self.splitter.epoch_finished():
            self.create_tasks()
        if not self.todo:
            if self.doing:
                return Task.wait_task()  # epoch may still be recovered
            return Task(task_id=-1, task_type=TaskType.NONE)
        task = self.todo.pop(0)
        self.doing[task.task_id] = DoingTask(task, node_id, time.time())
        return task

    def report_done(self, task_id: int, success: bool) -> Optional[Task]:
        doing = self.doing.pop(task_id, None)
        if doing is None:
            return None
        if not success:
            self.todo.insert(0, doing.task)
            return doing.task
        return None

    def recover_node_tasks(self, node_id: int) -> int:
        """Requeue all shards a dead node was working on."""
        recovered = 0
        for task_id in list(self.doing):
            if self.doing[task_id].node_id == node_id:
                doing = self.doing.pop(task_id)
                self.todo.insert(0, doing.task)
                recovered += 1
        return recovered

    def reassign_timeout_tasks(self, timeout: float) -> int:
        now = time.time()
        n = 0
        for task_id in list(self.doing):
            doing = self.doing[task_id]
            if now - doing.start_time > timeout:
                self.doing.pop(task_id)
                self.todo.insert(0, doing.task)
                n += 1
        if n:
            logger.warning("reassigned %d timed-out shards", n)
        return n

    def finished(self) -> bool:
        return (
            self.splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    def to_checkpoint(self) -> dict:
        """Snapshot undone shards so a restarted job resumes data exactly."""
        undone = [t for t in self.todo] + [
            d.task for d in self.doing.values()
        ]
        return {
            "splitter": self.splitter.to_checkpoint(),
            "todo": [
                {
                    "task_id": t.task_id,
                    "start": t.shard.start if t.shard else 0,
                    "end": t.shard.end if t.shard else 0,
                    "indices": t.shard.record_indices if t.shard else None,
                }
                for t in undone
            ],
            "next_task_id": self._task_id,
        }

    def restore_checkpoint(self, state: dict) -> None:
        self.splitter.restore_checkpoint(state.get("splitter", {}))
        self.todo = []
        self.doing = {}
        for t in state.get("todo", []):
            shard = Shard(
                name=self.splitter.dataset_name,
                start=t["start"],
                end=t["end"],
                record_indices=t.get("indices"),
            )
            self.todo.append(
                Task(
                    task_id=t["task_id"],
                    task_type=self.task_type,
                    shard=shard,
                )
            )
        self._task_id = state.get("next_task_id", len(self.todo))


class TaskManager:
    """All datasets of one job + the shard-timeout watchdog."""

    def __init__(self, shard_timeout: float = 300.0):
        self._lock = threading.Lock()
        self._datasets: Dict[str, DatasetManager] = {}
        self._completed_notified: set = set()
        self.shard_timeout = shard_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # callback(dataset_name) fired when a dataset completes
        self.on_dataset_complete: Optional[Callable[[str], None]] = None

    def create_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
        task_type: str = TaskType.TRAINING,
    ) -> None:
        with self._lock:
            if dataset_name in self._datasets:
                return
            splitter = new_dataset_splitter(
                storage_type,
                dataset_name,
                dataset_size,
                shard_size,
                num_epochs,
                shuffle,
            )
            self._datasets[dataset_name] = DatasetManager(
                splitter, task_type
            )

    def has_dataset(self, dataset_name: str) -> bool:
        with self._lock:
            return dataset_name in self._datasets

    def get_task(self, node_id: int, dataset_name: str) -> Task:
        completed = False
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                return Task.wait_task()
            task = ds.get_task(node_id)
            if (
                task.task_type == TaskType.NONE
                and ds.finished()
                and dataset_name not in self._completed_notified
            ):
                self._completed_notified.add(dataset_name)
                completed = True
        # Fire the callback OUTSIDE the lock: it may re-enter TaskManager.
        if completed and self.on_dataset_complete:
            self.on_dataset_complete(dataset_name)
        return task

    def report_task_result(
        self, dataset_name: str, task_id: int, success: bool
    ) -> None:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is not None:
                ds.report_done(task_id, success)

    def recover_node_tasks(self, node_id: int) -> None:
        with self._lock:
            for ds in self._datasets.values():
                ds.recover_node_tasks(node_id)

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(ds.finished() for ds in self._datasets.values())

    # -- checkpoint ---------------------------------------------------------

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                return ""
            return json.dumps(ds.to_checkpoint())

    def restore_shard_checkpoint(
        self, dataset_name: str, content: str
    ) -> bool:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None or not content:
                return False
            ds.restore_checkpoint(json.loads(content))
            return True

    # -- watchdog -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._watch_loop, name="shard-watchdog", daemon=True
        )
        self._thread.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(15.0):
            with self._lock:
                for ds in self._datasets.values():
                    ds.reassign_timeout_tasks(self.shard_timeout)

    def stop(self) -> None:
        self._stop.set()
