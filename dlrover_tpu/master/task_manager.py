"""Dynamic data sharding: the master-side task manager.

Parity: dlrover/python/master/shard/task_manager.py:37 (TaskManager) and
batch_dataset_manager.py. Shards flow todo -> doing -> done; a shard
assigned to a worker that dies or times out goes back to todo, which is
what gives exactly-once(-ish) data consumption under elasticity without
any coordination in the training processes.

Two contracts matter for control-plane survivability:

* **Idempotent result reports.** Agents retry ``TaskResultRequest``
  across reconnects, and a replayed report can arrive after the shard
  was already completed or re-queued to another node. A report only
  acts when its task is still in ``doing`` AND it comes from the
  shard's *current* assignee — a stale replay can neither double-count
  a shard nor yank it from the node now working on it.
* **Warm-restart snapshots.** ``to_snapshot``/``restore_snapshot``
  capture every dataset (creation params + shard ledger, with doing
  shards kept assigned to their node). In-flight shards stay with
  their owners across a master bounce (the watchdog re-queues them
  only if the owner never completes them), and completion reports
  request an urgent journal flush — so a journaled completion is
  never re-dispatched. The floor is still at-least-once: a completion
  acknowledged in the instant between the ack and the journal write
  reaching disk can be re-dispatched after ``shard_timeout`` if the
  master dies in that window.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.dataset_splitter import (
    DatasetSplitter,
    Shard,
    new_dataset_splitter,
)

logger = get_logger("task_manager")


@dataclasses.dataclass
class Task:
    task_id: int
    task_type: str
    shard: Optional[Shard] = None

    @classmethod
    def wait_task(cls) -> "Task":
        return cls(task_id=-1, task_type=TaskType.WAIT)


@dataclasses.dataclass
class DoingTask:
    task: Task
    node_id: int
    start_time: float  # monotonic: feeds the shard-timeout watchdog


def _task_to_dict(task: Task) -> dict:
    return {
        "task_id": task.task_id,
        "start": task.shard.start if task.shard else 0,
        "end": task.shard.end if task.shard else 0,
        "indices": task.shard.record_indices if task.shard else None,
        "partition": task.shard.partition if task.shard else 0,
    }


class DatasetManager:
    """Todo/doing bookkeeping for one named dataset."""

    def __init__(
        self,
        splitter: DatasetSplitter,
        task_type: str,
        params: Optional[dict] = None,
    ):
        self.splitter = splitter
        self.task_type = task_type
        # Creation parameters, kept verbatim so a warm-restarted
        # master can rebuild this manager before restoring its ledger.
        self.params = dict(params or {})
        self.todo: List[Task] = []
        self.doing: Dict[int, DoingTask] = {}
        self._task_id = 0
        self._completed_step = 0

    def create_tasks(self) -> None:
        if self.splitter.epoch_finished():
            return
        self.splitter.create_shards()
        for shard in self.splitter.get_shards():
            self.todo.append(
                Task(
                    task_id=self._task_id,
                    task_type=self.task_type,
                    shard=shard,
                )
            )
            self._task_id += 1

    def get_task(self, node_id: int) -> Task:
        if not self.todo and not self.splitter.epoch_finished():
            self.create_tasks()
        if not self.todo:
            if self.doing:
                return Task.wait_task()  # epoch may still be recovered
            return Task(task_id=-1, task_type=TaskType.NONE)
        task = self.todo.pop(0)
        self.doing[task.task_id] = DoingTask(
            task, node_id, time.monotonic()
        )
        return task

    def report_done(
        self, task_id: int, success: bool, node_id: Optional[int] = None
    ) -> Optional[Task]:
        """Record one result report. Idempotent against replays:

        * a task no longer in ``doing`` (already completed, already
          re-queued, or never dispatched) is a no-op;
        * a report whose ``node_id`` is not the shard's current
          assignee (the original owner replaying after the watchdog
          re-queued and re-dispatched the shard) is ignored.
        """
        doing = self.doing.get(task_id)
        if doing is None:
            return None  # already done / re-queued / never dispatched
        if (
            node_id is not None
            and node_id >= 0
            and doing.node_id != node_id
        ):
            logger.warning(
                "ignoring stale result for task %d from node %d "
                "(currently assigned to node %d)",
                task_id, node_id, doing.node_id,
            )
            return None
        del self.doing[task_id]
        if not success:
            self.todo.insert(0, doing.task)
            return doing.task
        shard = doing.task.shard
        if shard is not None and hasattr(self.splitter, "mark_done"):
            # Streaming ledgers advance the per-partition watermark —
            # the completion frontier a stream barrier stamps into PS
            # flushes.
            self.splitter.mark_done(
                shard.partition, shard.start, shard.end
            )
        return None

    def recover_node_tasks(self, node_id: int) -> int:
        """Requeue all shards a dead node was working on."""
        recovered = 0
        for task_id in list(self.doing):
            if self.doing[task_id].node_id == node_id:
                doing = self.doing.pop(task_id)
                self.todo.insert(0, doing.task)
                recovered += 1
        return recovered

    def reassign_timeout_tasks(self, timeout: float) -> int:
        now = time.monotonic()
        n = 0
        for task_id in list(self.doing):
            doing = self.doing[task_id]
            if now - doing.start_time > timeout:
                self.doing.pop(task_id)
                self.todo.insert(0, doing.task)
                n += 1
        if n:
            logger.warning("reassigned %d timed-out shards", n)
        return n

    def finished(self) -> bool:
        return (
            self.splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    def to_checkpoint(self) -> dict:
        """Snapshot undone shards so a restarted job resumes data
        exactly. ``todo`` holds unassigned shards; ``doing`` keeps the
        in-flight ones with their assignee, so a master warm restart
        can leave them with their owners instead of re-queueing work
        an agent is mid-way through (which would double-process it
        when the agent's completion report lands after reconnect)."""
        return {
            "splitter": self.splitter.to_checkpoint(),
            "todo": [_task_to_dict(t) for t in self.todo],
            "doing": [
                {**_task_to_dict(d.task), "node_id": d.node_id}
                for d in self.doing.values()
            ],
            "next_task_id": self._task_id,
        }

    def restore_checkpoint(
        self, state: dict, keep_doing: bool = False
    ) -> None:
        """``keep_doing=False`` (trainer-driven resume of a FRESH job:
        the old workers are gone) folds in-flight shards back into
        todo; ``keep_doing=True`` (master warm restart: the workers
        are still out there) restores them as doing with a fresh
        timeout clock."""
        self.splitter.restore_checkpoint(state.get("splitter", {}))
        self.todo = []
        self.doing = {}

        def _shard(t: dict) -> Shard:
            return Shard(
                name=self.splitter.dataset_name,
                start=t["start"],
                end=t["end"],
                record_indices=t.get("indices"),
                partition=int(t.get("partition", 0)),
            )

        def _task(t: dict) -> Task:
            return Task(
                task_id=t["task_id"],
                task_type=self.task_type,
                shard=_shard(t),
            )

        for t in state.get("todo", []):
            self.todo.append(_task(t))
        for t in state.get("doing", []):
            if keep_doing:
                self.doing[t["task_id"]] = DoingTask(
                    _task(t), int(t.get("node_id", -1)), time.monotonic()
                )
            else:
                self.todo.append(_task(t))
        self._task_id = state.get(
            "next_task_id", len(self.todo) + len(self.doing)
        )


class TaskManager:
    """All datasets of one job + the shard-timeout watchdog."""

    def __init__(self, shard_timeout: float = 300.0):
        self._lock = threading.Lock()
        self._datasets: Dict[str, DatasetManager] = {}
        self._completed_notified: set = set()
        # dataset -> last stream-barrier record (epoch, offsets,
        # watermarks, flush_gen); journaled with the snapshot.
        self._barriers: Dict[str, dict] = {}
        self.shard_timeout = shard_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # callback(dataset_name) fired when a dataset completes
        self.on_dataset_complete: Optional[Callable[[str], None]] = None
        # Fired (outside the lock) after every ledger mutation; the
        # JobMaster points this at the state journal's mark_dirty.
        # ``urgent=True`` (completion reports) asks the journal to
        # skip its debounce: once a completion is acknowledged to the
        # agent, the window in which a master death could resurrect
        # the shard must be the write latency, not the debounce
        # interval.
        self.on_state_change: Optional[Callable[..., None]] = None

    def _changed(self, urgent: bool = False) -> None:
        cb = self.on_state_change
        if cb is not None:
            try:
                # The callback must accept urgent= (StateJournal.
                # mark_dirty does; so must any test double).
                cb(urgent=urgent)
            except Exception:  # noqa: BLE001
                pass

    def create_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
        task_type: str = TaskType.TRAINING,
        num_stream_partitions: int = 1,
    ) -> None:
        params = {
            "dataset_name": dataset_name,
            "dataset_size": dataset_size,
            "shard_size": shard_size,
            "num_epochs": num_epochs,
            "shuffle": shuffle,
            "storage_type": storage_type,
            "task_type": task_type,
            "num_stream_partitions": num_stream_partitions,
        }
        with self._lock:
            if dataset_name in self._datasets:
                return
            splitter = new_dataset_splitter(
                storage_type,
                dataset_name,
                dataset_size,
                shard_size,
                num_epochs,
                shuffle,
                num_stream_partitions=num_stream_partitions,
            )
            self._datasets[dataset_name] = DatasetManager(
                splitter, task_type, params=params
            )
        self._changed()

    def has_dataset(self, dataset_name: str) -> bool:
        with self._lock:
            return dataset_name in self._datasets

    def get_task(self, node_id: int, dataset_name: str) -> Task:
        completed = False
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                return Task.wait_task()
            task = ds.get_task(node_id)
            if (
                task.task_type == TaskType.NONE
                and ds.finished()
                and dataset_name not in self._completed_notified
            ):
                self._completed_notified.add(dataset_name)
                completed = True
        # Fire the callback OUTSIDE the lock: it may re-enter TaskManager.
        if completed and self.on_dataset_complete:
            self.on_dataset_complete(dataset_name)
        if task.shard is not None:
            self._changed()
        return task

    def report_task_result(
        self,
        dataset_name: str,
        task_id: int,
        success: bool,
        node_id: Optional[int] = None,
    ) -> None:
        acted = False
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is not None:
                before = task_id in ds.doing
                ds.report_done(task_id, success, node_id=node_id)
                acted = before and task_id not in ds.doing
        # Urgent flush ONLY when the report actually retired or
        # re-queued a doing entry: a replay storm of no-op reports
        # after a mass reconnect must not become an fsync storm.
        if acted:
            self._changed(urgent=True)

    def recover_node_tasks(self, node_id: int) -> None:
        with self._lock:
            for ds in self._datasets.values():
                ds.recover_node_tasks(node_id)
        self._changed()

    # -- stream barriers ----------------------------------------------------

    def ledger_watermarks(self, dataset_name: str) -> dict:
        """Streaming ledger frontier: per-partition fabrication
        offsets, per-partition completion watermarks, and the total
        contiguously-applied record count (the barrier's HWM)."""
        with self._lock:
            ds = self._datasets.get(dataset_name)
            sp = ds.splitter if ds is not None else None
            if sp is None or not hasattr(sp, "watermarks"):
                return {"offsets": {}, "watermarks": {}, "records": 0}
            return {
                "offsets": dict(sp.part_offsets),
                "watermarks": dict(sp.watermarks),
                "records": sp.watermark_records(),
            }

    def record_barrier(
        self,
        dataset_name: str,
        epoch: int,
        step: int,
        flush_gen: int = 0,
        flushed_rows: int = 0,
    ) -> dict:
        """Pin the current streaming cut as the last barrier: (epoch,
        per-partition offsets + watermarks, PS flush generation) as one
        unit. Lives inside the warm-restart snapshot, so the journal
        write that makes it durable is the same one that makes the
        shard ledger durable — the atomicity the barrier contract
        needs."""
        frontier = self.ledger_watermarks(dataset_name)
        with self._lock:
            record = {
                "epoch": epoch,
                "step": step,
                "offsets": frontier["offsets"],
                "watermarks": frontier["watermarks"],
                "records": frontier["records"],
                "flush_gen": flush_gen,
                "flushed_rows": flushed_rows,
            }
            self._barriers[dataset_name] = record
        self._changed(urgent=True)
        return dict(record)

    def last_barrier(self, dataset_name: str) -> Optional[dict]:
        with self._lock:
            rec = self._barriers.get(dataset_name)
            return dict(rec) if rec else None

    def finished(self) -> bool:
        with self._lock:
            if not self._datasets:
                return False
            return all(ds.finished() for ds in self._datasets.values())

    # -- checkpoint ---------------------------------------------------------

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None:
                return ""
            return json.dumps(ds.to_checkpoint())

    def restore_shard_checkpoint(
        self, dataset_name: str, content: str
    ) -> bool:
        with self._lock:
            ds = self._datasets.get(dataset_name)
            if ds is None or not content:
                return False
            # Trainer-driven resume: the checkpoint's doing-owners are
            # from a previous job incarnation, so fold them into todo.
            ds.restore_checkpoint(json.loads(content), keep_doing=False)
        self._changed()
        return True

    # -- warm-restart snapshot ----------------------------------------------

    def to_snapshot(self) -> dict:
        """The whole shard ledger: per-dataset creation params +
        checkpoint state, so a restarted master can rebuild each
        DatasetManager and resume exactly."""
        with self._lock:
            return {
                "datasets": {
                    name: {
                        "params": dict(ds.params),
                        "state": ds.to_checkpoint(),
                    }
                    for name, ds in self._datasets.items()
                },
                "completed_notified": sorted(self._completed_notified),
                "barriers": {
                    name: dict(rec)
                    for name, rec in self._barriers.items()
                },
            }

    def reset(self) -> None:
        """Drop the whole ledger (cold-start cleanup when a warm
        restart fails half-way)."""
        with self._lock:
            self._datasets = {}
            self._completed_notified = set()
            self._barriers = {}

    def restore_snapshot(self, state: dict) -> None:
        for name, entry in state.get("datasets", {}).items():
            params = entry.get("params", {})
            self.create_dataset(
                dataset_name=params.get("dataset_name", name),
                dataset_size=int(params.get("dataset_size", 0)),
                shard_size=max(int(params.get("shard_size", 1)), 1),
                num_epochs=int(params.get("num_epochs", 1)),
                shuffle=bool(params.get("shuffle", False)),
                storage_type=params.get("storage_type", "table")
                or "table",
                task_type=params.get("task_type", TaskType.TRAINING)
                or TaskType.TRAINING,
                num_stream_partitions=int(
                    params.get("num_stream_partitions", 1)
                ),
            )
            with self._lock:
                ds = self._datasets[name]
                # Warm restart: the assignees are (probably) still
                # alive and mid-shard — keep doing as doing.
                ds.restore_checkpoint(
                    entry.get("state", {}), keep_doing=True
                )
        with self._lock:
            self._completed_notified = set(
                state.get("completed_notified", [])
            )
            # The JSON round-trip stringifies the per-partition dict
            # keys; the query path (StreamBarrierResponse) and the
            # live record_barrier path both speak int partitions.
            self._barriers = {}
            for name, rec in state.get("barriers", {}).items():
                rec = dict(rec)
                for field in ("offsets", "watermarks"):
                    rec[field] = {
                        int(p): int(v)
                        for p, v in rec.get(field, {}).items()
                    }
                self._barriers[name] = rec
        self._changed()

    # -- watchdog -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._watch_loop, name="shard-watchdog", daemon=True
        )
        self._thread.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(15.0):
            reassigned = 0
            with self._lock:
                for ds in self._datasets.values():
                    reassigned += ds.reassign_timeout_tasks(
                        self.shard_timeout
                    )
            if reassigned:
                self._changed()

    def stop(self) -> None:
        self._stop.set()
