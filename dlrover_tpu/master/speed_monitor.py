"""Training speed monitor on the master.

Parity: dlrover/python/master/monitor/speed_monitor.py:43. Collects
per-node step/token reports, maintains a moving throughput window, and
exposes straggler/degradation signals used by the auto-scaler and the
judge of post-recovery throughput ("time to 90% of pre-failure speed").

Straggler scoring: every per-step wall time a host reports (direct
timings in metric snapshots, or derived from step-report deltas)
feeds a per-host EWMA; a host whose EWMA exceeds ``straggler_ratio``
times the fleet median — with at least ``min_straggler_hosts`` hosts
and ``min_straggler_samples`` samples each, so a 2-host job can never
out-vote itself — is a straggler. Transitions emit a
``node.straggler`` event and bump ``dlrover_straggler_total``; the
verdict backs the ``query_stragglers`` RPC and the auto-scaler.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from statistics import median
from typing import Deque, Dict, List, Optional, Set, Tuple

from dlrover_tpu import obs

STRAGGLER_RATIO_ENV = "DLROVER_TPU_STRAGGLER_RATIO"

_STRAGGLERS_TOTAL = obs.counter(
    "dlrover_straggler_total",
    "Hosts newly scored as stragglers (step-time EWMA above "
    "straggler_ratio x fleet median)",
    ("node",),
)
_HOST_STEP_EWMA = obs.gauge(
    "dlrover_host_step_seconds_ewma",
    "Per-host EWMA of reported per-step wall time",
    ("node",),
)


class SpeedMonitor:
    def __init__(
        self,
        window: int = 20,
        recovery_ratio: float = 0.9,
        straggler_ratio: Optional[float] = None,
        ewma_alpha: float = 0.3,
        min_straggler_hosts: int = 3,
        min_straggler_samples: int = 3,
    ):
        self._lock = threading.Lock()
        # (timestamp, global_step, tokens_since_last)
        self._samples: Deque[Tuple[float, int, int]] = deque(maxlen=window)
        self._global_step = 0
        self._global_tokens = 0
        # world size (chips) per sample window, to normalize per-chip
        self._alive_nodes: Set[int] = set()
        self._node_steps: Dict[int, int] = {}
        # last (timestamp, step) per node, to derive per-step time
        # from step reports when no direct timings arrive
        self._node_last_report: Dict[int, Tuple[float, int]] = {}
        # throughput recorded immediately before the last failure event
        self._pre_failure_tput: Optional[float] = None
        self._last_failure_time: Optional[float] = None
        # First sample timestamp whose window crossed
        # recovery_ratio * pre-failure throughput: recorded when the
        # crossing sample ARRIVES, so a late recovery_seconds() poll
        # reports the true recovery time, not the poll time.
        self._recovery_ratio = recovery_ratio
        self._recovery_crossed_at: Optional[float] = None
        # straggler scoring state
        if straggler_ratio is None:
            straggler_ratio = float(
                os.getenv(STRAGGLER_RATIO_ENV, "") or 2.0
            )
        self.straggler_ratio = straggler_ratio
        self._ewma_alpha = ewma_alpha
        self._min_straggler_hosts = min_straggler_hosts
        self._min_straggler_samples = min_straggler_samples
        self._host_step_ewma: Dict[int, float] = {}
        self._host_step_samples: Dict[int, int] = {}
        self._known_stragglers: Set[int] = set()
        # Called with a node_id when it is NEWLY scored a straggler —
        # the JobMaster wires this to push a `diagnose` action so a
        # host that went slow gets a stack-and-state snapshot while
        # it is still being slow. Exceptions are swallowed: a broken
        # trigger must not poison step accounting.
        self.on_straggler = None
        # Optional TimeSeriesStore (set by the JobMaster): every EWMA
        # update is also recorded as history, so the health plane's
        # straggler-persistence detector has an evidence window.
        self.timeseries = None

    # -- throughput window ---------------------------------------------------

    def _running_speed_locked(self) -> float:
        if len(self._samples) < 2:
            return 0.0
        t0, s0, _ = self._samples[0]
        t1, s1, _ = self._samples[-1]
        if t1 <= t0:
            return 0.0
        return (s1 - s0) / (t1 - t0)

    def _token_throughput_locked(self) -> float:
        if len(self._samples) < 2:
            return 0.0
        t0 = self._samples[0][0]
        t1 = self._samples[-1][0]
        if t1 <= t0:
            return 0.0
        tokens = sum(s[2] for s in list(self._samples)[1:])
        return tokens / (t1 - t0)

    def _window_tput_locked(self) -> float:
        return (
            self._token_throughput_locked()
            or self._running_speed_locked()
        )

    def _post_failure_tput_locked(self, since: float) -> Optional[float]:
        """Throughput over the window samples at/after ``since`` only
        — pre-failure samples still sitting in the deque must not
        vouch for a recovery they predate. None until two post-failure
        samples exist."""
        post = [s for s in self._samples if s[0] >= since]
        if len(post) < 2:
            return None
        t0, t1 = post[0][0], post[-1][0]
        if t1 <= t0:
            return 0.0
        tokens = sum(s[2] for s in post[1:])
        if tokens > 0:
            return tokens / (t1 - t0)
        return (post[-1][1] - post[0][1]) / (t1 - t0)

    def _note_recovery_crossing_locked(self, timestamp: float) -> None:
        """Record the first window that regains the recovery-ratio
        throughput. Called with the lock held, after the window moved."""
        if (
            self._pre_failure_tput is None
            or self._last_failure_time is None
            or self._recovery_crossed_at is not None
            or timestamp < self._last_failure_time
        ):
            return
        tput = self._post_failure_tput_locked(self._last_failure_time)
        if (
            tput is not None
            and tput >= self._recovery_ratio * self._pre_failure_tput
        ):
            self._recovery_crossed_at = timestamp

    def collect_global_step(
        self, step: int, timestamp: float, tokens: int = 0
    ) -> None:
        with self._lock:
            self._global_step = max(self._global_step, step)
            self._global_tokens += tokens
            self._samples.append((timestamp, step, tokens))
            self._note_recovery_crossing_locked(timestamp)

    def collect_node_step(
        self, node_id: int, step: int, timestamp: Optional[float] = None
    ) -> None:
        ts = timestamp if timestamp is not None else time.time()
        with self._lock:
            self._node_steps[node_id] = step
            prev = self._node_last_report.get(node_id)
            self._node_last_report[node_id] = (ts, step)
        if prev is not None:
            prev_ts, prev_step = prev
            if step > prev_step and ts > prev_ts:
                # Per-step wall time implied by the report cadence —
                # coarser than direct snapshot timings but keeps the
                # straggler score alive for agents that only send
                # step reports.
                self.observe_host_step_time(
                    node_id, (ts - prev_ts) / (step - prev_step)
                )

    @property
    def global_step(self) -> int:
        with self._lock:
            return self._global_step

    def running_speed(self) -> float:
        """Steps/sec over the sample window."""
        with self._lock:
            return self._running_speed_locked()

    def token_throughput(self) -> float:
        """Tokens/sec over the sample window."""
        with self._lock:
            return self._token_throughput_locked()

    # -- failure / recovery tracking ----------------------------------------

    def add_running_node(self, node_id: int) -> None:
        with self._lock:
            self._alive_nodes.add(node_id)

    def remove_running_node(self, node_id: int) -> None:
        """Record a failure event: snapshot throughput for recovery SLO."""
        with self._lock:
            if node_id in self._alive_nodes:
                self._alive_nodes.discard(node_id)
                self._last_failure_time = time.time()
                self._recovery_crossed_at = None
                # Snapshot under the SAME lock acquisition: reading
                # the window between two acquisitions let a racing
                # collect_global_step shift it first, baselining the
                # recovery SLO on post-failure throughput.
                tput = self._window_tput_locked()
                if self._pre_failure_tput is None and tput > 0:
                    self._pre_failure_tput = tput
            # A departed host's step-time EWMA must not skew the
            # straggler median (nor linger in the fleet gauge).
            if self._host_step_ewma.pop(node_id, None) is not None:
                self._host_step_samples.pop(node_id, None)
                self._known_stragglers.discard(node_id)
                try:
                    _HOST_STEP_EWMA.remove(node=str(node_id))
                except ValueError:
                    pass
                if self.timeseries is not None:
                    self.timeseries.drop_series(
                        "host.step_ewma", node=str(node_id)
                    )
            self._node_last_report.pop(node_id, None)

    def recovery_seconds(
        self, ratio: Optional[float] = None
    ) -> Optional[float]:
        """Seconds from the last failure until the throughput window
        first regained ``ratio`` (default: the constructor's
        ``recovery_ratio``) of the pre-failure throughput, or None if
        not yet recovered / no failure observed.

        The crossing is timestamped when the crossing SAMPLE arrives
        (collect_global_step) and only post-failure samples vouch for
        it, so polling late no longer overstates the recovery time and
        a window still dominated by pre-failure samples cannot claim
        an instant recovery. When no sample has arrived since the
        failure at all, the legacy full-window check answers (a
        throughput that never dropped recovers in ~0s) without caching
        a crossing.
        """
        with self._lock:
            pre = self._pre_failure_tput
            fail_t = self._last_failure_time
            crossed = self._recovery_crossed_at
            if pre is None or fail_t is None:
                return None
            use_ratio = (
                self._recovery_ratio if ratio is None else ratio
            )
            if crossed is not None and use_ratio == self._recovery_ratio:
                return max(crossed - fail_t, 0.0)
            if any(s[0] >= fail_t for s in self._samples):
                # Post-failure traffic exists: only it may vouch for
                # the recovery (None until >= 2 post-failure samples).
                post_tput = self._post_failure_tput_locked(fail_t)
                if (
                    post_tput is not None
                    and post_tput >= use_ratio * pre
                ):
                    last_ts = self._samples[-1][0]
                    if use_ratio == self._recovery_ratio:
                        self._recovery_crossed_at = max(last_ts, fail_t)
                    return max(last_ts - fail_t, 0.0)
                return None
            # No sample since the failure at all: the legacy
            # full-window answer (a throughput that never dropped
            # recovers in ~0s), deliberately not cached.
            if self._window_tput_locked() >= use_ratio * pre:
                last_ts = (
                    self._samples[-1][0] if self._samples else fail_t
                )
                return max(last_ts - fail_t, 0.0)
        return None

    # -- warm-restart snapshot ----------------------------------------------

    def to_snapshot(self) -> dict:
        """Progress facts worth surviving a master restart: the
        global step/token high-water marks and per-node steps. Window
        samples and EWMAs are deliberately dropped — throughput and
        straggler scores re-warm from live traffic in seconds, and
        stale samples would claim a throughput the restarted fleet
        has not demonstrated."""
        with self._lock:
            return {
                "global_step": self._global_step,
                "global_tokens": self._global_tokens,
                "node_steps": {
                    str(k): v for k, v in self._node_steps.items()
                },
                "alive_nodes": sorted(self._alive_nodes),
            }

    def restore_snapshot(self, state: dict) -> None:
        with self._lock:
            self._global_step = int(state.get("global_step", 0))
            self._global_tokens = int(state.get("global_tokens", 0))
            self._node_steps = {
                int(k): int(v)
                for k, v in state.get("node_steps", {}).items()
            }
            self._alive_nodes = {
                int(n) for n in state.get("alive_nodes", [])
            }

    def reset_failure_tracking(self) -> None:
        with self._lock:
            self._pre_failure_tput = None
            self._last_failure_time = None
            self._recovery_crossed_at = None

    def all_nodes_caught_up(self) -> bool:
        """True when every alive node reported the current global step."""
        with self._lock:
            if not self._alive_nodes:
                return False
            return all(
                self._node_steps.get(n, -1) >= self._global_step
                for n in self._alive_nodes
            )

    # -- straggler scoring ---------------------------------------------------

    def observe_host_step_time(
        self, node_id: int, step_time: float
    ) -> None:
        """Fold one per-step wall time into the host's EWMA."""
        if node_id < 0 or step_time <= 0:
            return
        with self._lock:
            prev = self._host_step_ewma.get(node_id)
            if prev is None:
                ewma = float(step_time)
            else:
                a = self._ewma_alpha
                ewma = a * float(step_time) + (1.0 - a) * prev
            self._host_step_ewma[node_id] = ewma
            self._host_step_samples[node_id] = (
                self._host_step_samples.get(node_id, 0) + 1
            )
        _HOST_STEP_EWMA.set(ewma, node=str(node_id))
        if self.timeseries is not None:
            self.timeseries.record(
                "host.step_ewma", ewma, node=str(node_id)
            )
        self._refresh_stragglers()

    def host_step_ewma(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._host_step_ewma)

    def straggler_scores(self) -> Dict[int, float]:
        """Per-host EWMA / fleet-median ratio, for hosts with enough
        samples. Empty below the minimum host count — relative
        slowness is meaningless for a fleet of one (or two, where the
        median IS one of the two hosts)."""
        with self._lock:
            scored = {
                n: e
                for n, e in self._host_step_ewma.items()
                if self._host_step_samples.get(n, 0)
                >= self._min_straggler_samples
            }
            if len(scored) < self._min_straggler_hosts:
                return {}
            fleet_median = median(scored.values())
            if fleet_median <= 0:
                return {}
            return {n: e / fleet_median for n, e in scored.items()}

    def stragglers(self) -> List[int]:
        """Node ids currently scored slower than ``straggler_ratio`` x
        the fleet median."""
        return sorted(
            n
            for n, score in self.straggler_scores().items()
            if score > self.straggler_ratio
        )

    def _refresh_stragglers(self) -> None:
        """Re-score and emit events/counters on transitions."""
        scores = self.straggler_scores()
        current = {
            n for n, s in scores.items() if s > self.straggler_ratio
        }
        with self._lock:
            fresh = current - self._known_stragglers
            recovered = self._known_stragglers - current
            self._known_stragglers = current
        for node_id in sorted(fresh):
            _STRAGGLERS_TOTAL.inc(node=str(node_id))
            obs.event(
                "node.straggler",
                node_id=node_id,
                score=round(scores[node_id], 3),
                ratio=self.straggler_ratio,
                ewma_s=round(
                    self._host_step_ewma.get(node_id, 0.0), 6
                ),
            )
            if self.on_straggler is not None:
                try:
                    self.on_straggler(node_id)
                except Exception:  # noqa: BLE001
                    pass
        for node_id in sorted(recovered):
            obs.event(
                "node.straggler_recovered",
                node_id=node_id,
                score=round(scores.get(node_id, 0.0), 3),
            )
