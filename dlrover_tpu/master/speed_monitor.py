"""Training speed monitor on the master.

Parity: dlrover/python/master/monitor/speed_monitor.py:43. Collects
per-node step/token reports, maintains a moving throughput window, and
exposes straggler/degradation signals used by the auto-scaler and the
judge of post-recovery throughput ("time to 90% of pre-failure speed").
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Set, Tuple


class SpeedMonitor:
    def __init__(self, window: int = 20):
        self._lock = threading.Lock()
        # (timestamp, global_step, tokens_since_last)
        self._samples: Deque[Tuple[float, int, int]] = deque(maxlen=window)
        self._global_step = 0
        self._global_tokens = 0
        self._start_time = time.time()
        # world size (chips) per sample window, to normalize per-chip
        self._alive_nodes: Set[int] = set()
        self._node_steps: Dict[int, int] = {}
        # throughput recorded immediately before the last failure event
        self._pre_failure_tput: Optional[float] = None
        self._last_failure_time: Optional[float] = None

    def collect_global_step(
        self, step: int, timestamp: float, tokens: int = 0
    ) -> None:
        with self._lock:
            self._global_step = max(self._global_step, step)
            self._global_tokens += tokens
            self._samples.append((timestamp, step, tokens))

    def collect_node_step(self, node_id: int, step: int) -> None:
        with self._lock:
            self._node_steps[node_id] = step

    @property
    def global_step(self) -> int:
        with self._lock:
            return self._global_step

    def running_speed(self) -> float:
        """Steps/sec over the sample window."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            t0, s0, _ = self._samples[0]
            t1, s1, _ = self._samples[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    def token_throughput(self) -> float:
        """Tokens/sec over the sample window."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            t0 = self._samples[0][0]
            t1 = self._samples[-1][0]
            if t1 <= t0:
                return 0.0
            tokens = sum(s[2] for s in list(self._samples)[1:])
            return tokens / (t1 - t0)

    def add_running_node(self, node_id: int) -> None:
        with self._lock:
            self._alive_nodes.add(node_id)

    def remove_running_node(self, node_id: int) -> None:
        """Record a failure event: snapshot throughput for recovery SLO."""
        with self._lock:
            if node_id in self._alive_nodes:
                self._alive_nodes.discard(node_id)
                self._last_failure_time = time.time()
        tput = self.token_throughput() or self.running_speed()
        with self._lock:
            if self._pre_failure_tput is None and tput > 0:
                self._pre_failure_tput = tput

    def recovery_seconds(self, ratio: float = 0.9) -> Optional[float]:
        """Seconds from last failure until throughput >= ratio * pre-failure,
        or None if not yet recovered / no failure observed."""
        with self._lock:
            pre = self._pre_failure_tput
            fail_t = self._last_failure_time
        if pre is None or fail_t is None:
            return None
        current = self.token_throughput() or self.running_speed()
        if current >= ratio * pre:
            return time.time() - fail_t
        return None

    def reset_failure_tracking(self) -> None:
        with self._lock:
            self._pre_failure_tput = None
            self._last_failure_time = None

    def all_nodes_caught_up(self) -> bool:
        """True when every alive node reported the current global step."""
        with self._lock:
            if not self._alive_nodes:
                return False
            return all(
                self._node_steps.get(n, -1) >= self._global_step
                for n in self._alive_nodes
            )
