"""Platform scalers and node watchers.

Capability parity with the reference's scaler/watcher layer
(dlrover/python/master/scaler/pod_scaler.py:71 PodScaler — creates
Pods+Services directly; elasticjob_scaler.py ElasticJobScaler —
patches a ScalePlan CRD; watcher/k8s_watcher.py PodWatcher), adapted
to TPU scheduling: the unit of scaling is a *host with attached TPU
chips* (a GKE TPU pod-slice member), and pod specs carry the TPU
topology selectors instead of GPU resource requests.

The k8s API surface is behind the small ``ClusterClient`` interface so
the master logic is testable against ``FakeClusterClient`` (the
reference achieves the same with MagicMock monkey-patching,
tests/test_utils.py:244-259 — a real seam beats mocks).
"""

from __future__ import annotations

import copy
import itertools
import queue
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

from dlrover_tpu.common.constants import NodeEventType, NodeStatus, NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.job_manager import ScalePlan, Scaler

logger = get_logger("scaler")


# ---------------------------------------------------------------------------
# Cluster client seam
# ---------------------------------------------------------------------------


class ClusterClient:
    """Minimal cluster-API surface the scaler needs."""

    def create_pod(self, spec: Dict) -> None:
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def list_pods(self, job_name: str) -> List[Dict]:
        raise NotImplementedError

    def create_service(self, spec: Dict) -> None:
        raise NotImplementedError

    def patch_custom_object(self, name: str, body: Dict) -> None:
        raise NotImplementedError

    def watch_pods(self, job_name: str) -> Iterator[Dict]:
        raise NotImplementedError


class FakeClusterClient(ClusterClient):
    """In-memory cluster for tests and local drills: pods 'start'
    instantly; ``fail_pod``/``preempt_pod`` inject faults."""

    def __init__(self):
        self.pods: Dict[str, Dict] = {}
        self.services: Dict[str, Dict] = {}
        self.custom_objects: Dict[str, Dict] = {}
        self.events: "queue.Queue[Dict]" = queue.Queue()
        self.create_errors = 0  # set >0 to make creates fail N times

    def create_pod(self, spec: Dict) -> None:
        if self.create_errors > 0:
            self.create_errors -= 1
            raise RuntimeError("simulated pod create failure")
        name = spec["name"]
        existing = self.pods.get(name)
        if existing is not None and existing.get("phase") == "Running":
            # Replayed plan (retried scale RPC, duplicate ScalePlan):
            # the pod is already there — the real apiserver answers
            # 409 AlreadyExists; emitting a second ADDED event here
            # would double-register the node with the job manager.
            return
        pod = dict(spec, phase="Running")
        self.pods[name] = pod
        self.events.put({"type": "ADDED", "pod": copy.deepcopy(pod)})

    def delete_pod(self, name: str) -> None:
        pod = self.pods.pop(name, None)
        if pod is not None:
            pod["phase"] = "Deleted"
            self.events.put(
                {"type": "DELETED", "pod": copy.deepcopy(pod)}
            )

    def list_pods(self, job_name: str) -> List[Dict]:
        return [
            copy.deepcopy(p)
            for p in self.pods.values()
            if p.get("job") == job_name
        ]

    def create_service(self, spec: Dict) -> None:
        self.services[spec["name"]] = spec

    def patch_custom_object(self, name: str, body: Dict) -> None:
        # Merge-patch semantics, like the real apiserver: a status
        # patch must not clobber the object's spec.
        def merge(dst: Dict, src: Dict) -> None:
            for k, v in src.items():
                if isinstance(v, dict) and isinstance(
                    dst.get(k), dict
                ):
                    merge(dst[k], v)
                else:
                    dst[k] = v

        obj = self.custom_objects.setdefault(name, {})
        merge(obj, body)

    def watch_pods(self, job_name: str) -> Iterator[Dict]:
        while True:
            evt = self.events.get()
            if evt is None:  # sentinel for shutdown
                return
            if evt["pod"].get("job") == job_name:
                yield evt

    # fault injection for drills
    def fail_pod(self, name: str, reason: str = "Error") -> None:
        pod = self.pods.pop(name, None)
        if pod is not None:
            pod["phase"] = "Failed"
            pod["reason"] = reason
            self.events.put(
                {"type": "MODIFIED", "pod": copy.deepcopy(pod)}
            )

    def preempt_pod(self, name: str) -> None:
        self.fail_pod(name, reason="Preempted")


# ---------------------------------------------------------------------------
# Pod scaler
# ---------------------------------------------------------------------------


class TPUPodScaler(Scaler):
    """Realizes ScalePlans as pod create/delete calls (ref PodScaler
    pod_scaler.py:143 ``scale``, :376 ``_create_pod``, :486 service
    creation). Pods are retried through a background queue the same
    way (:349 ``_periodic_create_pod``)."""

    def __init__(
        self,
        job_name: str,
        client: ClusterClient,
        pod_template: Optional[Dict] = None,
        retry_interval: float = 3.0,
        max_create_retries: int = 5,
    ):
        super().__init__()
        self.job_name = job_name
        self.client = client
        self.pod_template = pod_template or {}
        self._create_q: "queue.Queue[Optional[Node]]" = queue.Queue()
        self._retry_interval = retry_interval
        self._max_create_retries = max_create_retries
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._create_loop, name="pod-creator", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._create_q.put(None)

    def pod_name(self, node: Node) -> str:
        return f"{self.job_name}-{node.type}-{node.id}"

    def scale(self, plan: ScalePlan) -> None:
        super().scale(plan)
        for node in plan.remove_nodes:
            try:
                self.client.delete_pod(self.pod_name(node))
            except Exception:  # noqa: BLE001
                logger.warning(
                    "delete pod %s failed", self.pod_name(node),
                    exc_info=True,
                )
        for node in plan.launch_nodes:
            self._create_q.put(node)
        # synchronous drain when no background thread is running
        if self._thread is None:
            self._drain_once()

    def _pod_spec(self, node: Node) -> Dict:
        res = node.config_resource or NodeResource()
        spec = dict(self.pod_template)
        spec.update(
            {
                "name": self.pod_name(node),
                "job": self.job_name,
                "type": node.type,
                "node_id": node.id,
                "rank": node.rank,
                "cpu": res.cpu,
                "memory_mb": res.memory_mb,
                # TPU scheduling: GKE selects node pools by these
                # (cloud.google.com/gke-tpu-accelerator + topology).
                "tpu_accelerator": res.tpu_type,
                "tpu_chips": res.chips,
                # multi-slice: pin the pod to its slice's node pool so
                # the replacement lands where the dead host was
                # (None = single-slice, no pin)
                "tpu_slice": (
                    res.slice_id if res.slice_id >= 0 else None
                ),
            }
        )
        return spec

    def _create_node(self, node: Node) -> bool:
        spec = self._pod_spec(node)
        try:
            self.client.create_pod(spec)
            self.client.create_service(
                {
                    "name": spec["name"],
                    "job": self.job_name,
                    "selector": spec["name"],
                }
            )
            return True
        except Exception:  # noqa: BLE001
            logger.warning(
                "create pod %s failed", spec["name"], exc_info=True
            )
            return False

    def _drain_once(self) -> None:
        while True:
            try:
                node = self._create_q.get_nowait()
            except queue.Empty:
                return
            if node is None:
                return
            for attempt in range(self._max_create_retries):
                if self._create_node(node):
                    break
                if self._thread is not None:
                    time.sleep(self._retry_interval)
            else:
                logger.error(
                    "giving up creating pod for node %d after %d "
                    "retries",
                    node.id,
                    self._max_create_retries,
                )

    def _create_loop(self) -> None:
        while not self._stop.is_set():
            node = self._create_q.get()
            if node is None:
                return
            for attempt in range(self._max_create_retries):
                if self._create_node(node):
                    break
                time.sleep(self._retry_interval)


class ElasticJobScaler(Scaler):
    """Writes the plan into a ScalePlan custom object for an external
    operator to realize (ref elasticjob_scaler.py)."""

    def __init__(self, job_name: str, client: ClusterClient):
        super().__init__()
        self.job_name = job_name
        self.client = client
        self._plan_index = itertools.count()

    def scale(self, plan: ScalePlan) -> None:
        super().scale(plan)
        from dlrover_tpu.scheduler.factory import scaleplan_manifest

        name = f"{self.job_name}-scaleplan-{next(self._plan_index)}"
        # One manifest shape everywhere: the operator-compatible
        # ScaleSpec (scheduler/factory.py, golden-file tested).
        body = scaleplan_manifest(name, self.job_name, plan)
        self.client.patch_custom_object(name, body)


# ---------------------------------------------------------------------------
# Watcher: cluster events -> job manager
# ---------------------------------------------------------------------------


_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Deleted": NodeStatus.DELETED,
}


class PodEventWatcher:
    """Relays pod events into JobManager node updates (ref PodWatcher
    k8s_watcher.py: event -> _process_event dist_job_manager.py:401)."""

    def __init__(self, job_name: str, client: ClusterClient, job_manager):
        self.job_name = job_name
        self.client = client
        self.job_manager = job_manager
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._watch_loop, name="pod-watcher", daemon=True
        )
        self._thread.start()

    def _watch_loop(self) -> None:
        try:
            for evt in self.client.watch_pods(self.job_name):
                self.process_event(evt)
        except Exception:  # noqa: BLE001
            logger.warning("pod watch loop ended", exc_info=True)

    def process_event(self, evt: Dict) -> None:
        pod = evt["pod"]
        node_id = pod.get("node_id")
        if node_id is None:
            return
        status = _PHASE_TO_STATUS.get(pod.get("phase", ""), "")
        if not status:
            return
        if status in (NodeStatus.FAILED, NodeStatus.DELETED):
            reason = pod.get("reason", "")
            self.job_manager.handle_node_gone(
                node_id, reason=reason
            )
        elif status == NodeStatus.RUNNING:
            node = self.job_manager.get_node(node_id)
            if node is not None:
                node.update_status(NodeStatus.RUNNING)
