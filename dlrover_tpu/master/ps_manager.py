"""Master-side manager for the PS-elastic sparse path.

Capability parity with the reference's PS node management
(dlrover/python/master/node/ps.py:1-369 ParameterServerManager — alive
PS set, pending migration, sync barrier before dropping a PS) and the
worker SyncService (master/elastic_training/sync_service.py), built on
the versioned PartitionMap instead of node-granular migration:

* PS nodes register their RPC address; the manager assigns hash
  partitions (sparse/partition.py:balanced_assignment — minimal-move).
* scale-up/down is an orchestrated move: freeze on source -> target
  pulls (PS-to-PS delta export/import incl. optimizer slots) -> map
  version bump -> unfreeze. Workers carrying the old version get
  rejected and refetch — no barrier RPC needed.
* a dead PS (failure report / heartbeat timeout) gets its partitions
  reassigned to survivors, who restore them from the per-partition
  delta checkpoint files (ps_server.flush) — the sparse analogue of
  flash-checkpoint recovery.
* periodic PS telemetry (qps/cpu/rows) feeds the hot-PS auto-scaler
  (master/auto_scaler.py:PsAutoScaler; ref local_optimizer.py:66).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.sparse.partition import (
    NUM_PARTITIONS,
    PartitionMap,
    balanced_assignment,
)

logger = get_logger("ps_manager")


class PsManager:
    def __init__(self, num_partitions: int = NUM_PARTITIONS):
        self.num_partitions = num_partitions
        self._lock = threading.RLock()
        self._map = PartitionMap(version=0, assignment=[], ps_addrs={})
        self._clients: Dict[int, RpcClient] = {}
        self._stats: Dict[int, msg.PsStatsReport] = {}
        self._stats_time: Dict[int, float] = {}
        self._ping_failures: Dict[int, int] = {}
        self._liveness_stop = threading.Event()
        self._liveness_thread: Optional[threading.Thread] = None
        # Set by check_liveness after an automatic failover: ps_id,
        # t_detected, t_map_published, map_version (drill telemetry).
        self.last_failover: Optional[Dict] = None
        # Fired after every membership/map mutation; the JobMaster
        # points this at the state journal's mark_dirty so the map
        # survives a master bounce (to_snapshot/restore_snapshot).
        self.on_state_change: Optional[Callable[..., None]] = None

    def _changed(self, urgent: bool = False) -> None:
        cb = self.on_state_change
        if cb is not None:
            try:
                cb(urgent=urgent)
            except Exception:  # noqa: BLE001
                pass

    # -- accessors -------------------------------------------------------

    @property
    def partition_map(self) -> PartitionMap:
        with self._lock:
            return PartitionMap(
                version=self._map.version,
                assignment=list(self._map.assignment),
                ps_addrs=dict(self._map.ps_addrs),
            )

    # -- warm-restart snapshot -------------------------------------------

    def to_snapshot(self) -> dict:
        """The partition map is recoverable master state: PS nodes
        outlive a master bounce, and a replacement master that forgot
        the map would re-rebalance healthy nodes from scratch (and
        break every fenced client mid-stream)."""
        with self._lock:
            return {
                "version": self._map.version,
                "assignment": list(self._map.assignment),
                "ps_addrs": {
                    str(ps): addr
                    for ps, addr in self._map.ps_addrs.items()
                },
            }

    def restore_snapshot(self, state: dict) -> None:
        """Adopt a journaled map without republishing: the PS fleet
        still holds these exact partitions at this exact version, so
        the restored master just resumes serving the map."""
        with self._lock:
            if not state:
                self._map = PartitionMap(
                    version=0, assignment=[], ps_addrs={}
                )
                return
            self._map = PartitionMap(
                version=int(state.get("version", 0)),
                assignment=[
                    int(a) for a in state.get("assignment", [])
                ],
                ps_addrs={
                    int(ps): addr
                    for ps, addr in state.get("ps_addrs", {}).items()
                },
            )
            self._clients = {}
            self._ping_failures = {}

    def to_msg(self) -> msg.PartitionMapMsg:
        m = self.partition_map
        return msg.PartitionMapMsg(
            version=m.version,
            assignment=m.assignment,
            ps_addrs=m.ps_addrs,
        )

    def _client(self, ps_id: int) -> RpcClient:
        # Takes the (reentrant) lock itself: callers on the liveness
        # thread and flush path run outside locked sections, and the
        # cache must not race register_ps/remove_ps closing entries.
        with self._lock:
            addr = self._map.ps_addrs[ps_id]
            c = self._clients.get(ps_id)
            if c is None or c.addr != addr:
                if c is not None:
                    c.close()
                c = RpcClient(addr)
                self._clients[ps_id] = c
            return c

    # -- membership ------------------------------------------------------

    def register_ps(self, ps_id: int, addr: str) -> None:
        """A PS node came up (fresh or relaunched). Rebalance minimal-
        move, migrate data for partitions that change owner, publish."""
        with self._lock:
            is_new = ps_id not in self._map.ps_addrs
            self._map.ps_addrs[ps_id] = addr
            self._clients.pop(ps_id, None)
            self._ping_failures.pop(ps_id, None)
            if is_new or not self._map.assignment:
                self._rebalance(reason=f"register ps {ps_id}")
            else:
                # Same node re-registered (restart in place): it lost
                # its memory — restore its partitions from checkpoint
                # and bump the version so workers re-resolve the addr.
                self._map = PartitionMap(
                    version=self._map.version + 1,
                    assignment=list(self._map.assignment),
                    ps_addrs=dict(self._map.ps_addrs),
                )
                for other in sorted(self._map.ps_addrs):
                    parts = self._map.partitions_of(other)
                    self._publish(
                        other, parts,
                        restore=parts if other == ps_id else None,
                    )
        self._changed(urgent=True)

    def remove_ps(self, ps_id: int) -> None:
        """A PS died or is being scaled in. Survivors take over its
        partitions and restore them from the flush dir."""
        with self._lock:
            if ps_id not in self._map.ps_addrs:
                return
            dead_parts = self._map.partitions_of(ps_id)
            del self._map.ps_addrs[ps_id]
            c = self._clients.pop(ps_id, None)
            if c is not None:
                c.close()
            self._stats.pop(ps_id, None)
            if not self._map.ps_addrs:
                logger.error("last PS node %d removed", ps_id)
                self._map.assignment = []
                self._map.version += 1
            else:
                self._rebalance(
                    reason=f"remove ps {ps_id}",
                    restore_parts=dead_parts,
                )
        self._changed(urgent=True)

    def drain_ps(self, ps_id: int) -> None:
        """Gracefully retire a still-alive PS (hot-PS migration, scale
        -in): its partitions move PS-to-PS to the survivors (freeze ->
        pull -> publish) instead of being restored from checkpoint —
        the live analogue of the reference's migrate-then-drop
        (master/node/ps.py:327 _migrate_parameter_server)."""
        with self._lock:
            if ps_id not in self._map.ps_addrs:
                return
            if len(self._map.ps_addrs) > 1:
                # The rebalance publishes the new map (version bump)
                # to the survivors; the drained node just drops out of
                # the address book afterwards — no second bump, or the
                # published version would go stale immediately.
                self._rebalance(
                    reason=f"drain ps {ps_id}", exclude=ps_id
                )
                del self._map.ps_addrs[ps_id]
                c = self._clients.pop(ps_id, None)
                if c is not None:
                    c.close()
                self._stats.pop(ps_id, None)
                self._changed(urgent=True)
                return
        # Last PS: nothing to move to — plain removal (checkpoint
        # restore is the only recovery once a new PS appears).
        self.remove_ps(ps_id)

    # -- rebalancing -----------------------------------------------------

    def _rebalance(self, reason: str,
                   restore_parts: Optional[List[int]] = None,
                   exclude: Optional[int] = None) -> None:
        """Compute the minimal-move assignment and execute the
        migration plan. Must hold the lock. ``exclude``: a still-alive
        node to plan around — it gets no partitions in the new map but
        remains a valid pull source for the moves."""
        ps_ids = sorted(
            i for i in self._map.ps_addrs if i != exclude
        )
        old = self._map
        new_assignment = balanced_assignment(
            ps_ids, self.num_partitions, previous=old
        )
        moves: Dict[int, Dict[int, List[int]]] = {}  # dst -> src -> [p]
        fresh: Dict[int, List[int]] = {}  # dst -> partitions w/o source
        restore_set = set(restore_parts or [])
        for p, dst in enumerate(new_assignment):
            src = (old.assignment[p]
                   if p < len(old.assignment) else None)
            if src == dst:
                continue
            if (src is None or src not in self._map.ps_addrs
                    or p in restore_set):
                fresh.setdefault(dst, []).append(p)
            else:
                moves.setdefault(dst, {}).setdefault(src, []).append(p)

        # 1. freeze moving partitions on their sources
        for dst, by_src in moves.items():
            for src, parts in by_src.items():
                self._safe_call(src, msg.PsFreezeRequest(
                    partitions=parts, frozen=True))
        # 2. targets pull from sources (PS-to-PS)
        for dst, by_src in moves.items():
            for src, parts in by_src.items():
                self._safe_call(dst, msg.PsPullPartitionsRequest(
                    source_addr=self._map.ps_addrs[src],
                    partitions=parts,
                ))
        # 3. publish the new map (version bump) to every PS
        self._map = PartitionMap(
            version=old.version + 1,
            assignment=new_assignment,
            ps_addrs=dict(self._map.ps_addrs),
        )
        for ps_id in ps_ids:
            parts = self._map.partitions_of(ps_id)
            restore = sorted(set(fresh.get(ps_id, [])) & set(parts))
            self._publish(ps_id, parts, restore=restore)
        logger.info(
            "partition map v%d (%s): %s",
            self._map.version, reason,
            {ps: len(self._map.partitions_of(ps)) for ps in ps_ids},
        )

    def _publish(self, ps_id: int, parts: List[int],
                 restore: Optional[List[int]] = None) -> None:
        if restore:
            self._safe_call(ps_id, msg.PsRestoreRequest(
                partitions=restore))
        self._safe_call(ps_id, msg.PsSetPartitionsRequest(
            partitions=parts, map_version=self._map.version))

    def _safe_call(self, ps_id: int, request) -> None:
        try:
            self._client(ps_id).get(request)
        except Exception:  # noqa: BLE001 — a dying PS must not wedge
            logger.warning(
                "PS %d rpc %s failed", ps_id,
                type(request).__name__, exc_info=True,
            )

    # -- checkpoint ------------------------------------------------------

    def flush_all(self, step: int, epoch: int = -1,
                  hwm: Optional[Dict[str, int]] = None) -> int:
        """Direct every PS to delta-flush (called on the trainer's
        checkpoint cadence). Returns total rows flushed.

        A stream barrier passes ``epoch`` and the shard ledger's
        high-water mark ``hwm``; both land in every partition's fence
        file, tying the PS cut to the ledger cut."""
        total = 0
        with self._lock:
            ps_ids = sorted(self._map.ps_addrs)
        for ps_id in ps_ids:
            try:
                resp = self._client(ps_id).get(msg.PsFlushRequest(
                    step=step, epoch=epoch, hwm=dict(hwm or {})))
                total += resp.flushed_rows
            except Exception:  # noqa: BLE001
                logger.warning("PS %d flush failed", ps_id,
                               exc_info=True)
        return total

    # -- liveness --------------------------------------------------------

    def start_liveness_monitor(
        self,
        interval: float = 2.0,
        failure_threshold: int = 2,
        ping_timeout: float = 3.0,
    ) -> None:
        """Detect abrupt PS death and fail it over automatically.

        Each tick pings every registered PS with a stats RPC; after
        ``failure_threshold`` consecutive failures the node is treated
        as dead and :meth:`remove_ps` runs — survivors take over its
        partitions restored from the last delta flush, the map version
        bumps, and blocked clients unblock on their next map refresh.
        Complements (and works without) the master's node-event path,
        e.g. for in-process drills with no servicer heartbeats.

        Invariant the defaults must keep: worst-case detection latency
        — ``failure_threshold * (interval + ping_timeout)`` = 10 s —
        must stay well inside the sparse client's stale-map retry
        budget (DistributedKvClient: max_retries backoff totalling
        ~39 s), or a blocked training step would exhaust its retries
        and crash before the new map is published.
        """
        if self._liveness_thread is not None:
            return
        self._liveness_stop.clear()

        def loop() -> None:
            while not self._liveness_stop.wait(interval):
                self.check_liveness(failure_threshold, ping_timeout)

        self._liveness_thread = threading.Thread(
            target=loop, name="ps-liveness", daemon=True
        )
        self._liveness_thread.start()

    def stop_liveness_monitor(self) -> None:
        self._liveness_stop.set()
        if self._liveness_thread is not None:
            self._liveness_thread.join(timeout=5.0)
            self._liveness_thread = None

    def check_liveness(
        self, failure_threshold: int = 2, ping_timeout: float = 3.0
    ) -> List[int]:
        """One liveness pass; returns the PS ids failed over."""
        with self._lock:
            ps_ids = sorted(self._map.ps_addrs)
        dead: List[int] = []
        for ps_id in ps_ids:
            try:
                self._client(ps_id).get(
                    msg.PsStatsRequest(), timeout=ping_timeout
                )
            except Exception:  # noqa: BLE001 — any failure counts
                with self._lock:
                    if ps_id not in self._map.ps_addrs:
                        # Deliberately removed (drain/remove) while we
                        # were pinging: not a strike.
                        self._ping_failures.pop(ps_id, None)
                        continue
                    self._ping_failures[ps_id] = (
                        self._ping_failures.get(ps_id, 0) + 1
                    )
                    failures = self._ping_failures[ps_id]
                logger.warning(
                    "PS %d liveness ping failed (%d/%d)",
                    ps_id, failures, failure_threshold,
                )
                if failures >= failure_threshold:
                    dead.append(ps_id)
            else:
                with self._lock:
                    self._ping_failures.pop(ps_id, None)
        for ps_id in dead:
            logger.error(
                "PS %d unresponsive for %d pings; failing over",
                ps_id, failure_threshold,
            )
            with self._lock:
                self._ping_failures.pop(ps_id, None)
            t_detected = time.time()
            self.remove_ps(ps_id)
            with self._lock:
                # Phase record for chaos drills: when the monitor
                # declared death vs when the rebalanced map published.
                self.last_failover = {
                    "ps_id": ps_id,
                    "t_detected": t_detected,
                    "t_map_published": time.time(),
                    "map_version": self._map.version,
                }
        return dead

    # -- telemetry -------------------------------------------------------

    def report_stats(self, report: msg.PsStatsReport) -> None:
        with self._lock:
            self._stats[report.node_id] = report
            # Monotonic arrival stamp: only compared against now() in
            # the max_age staleness sweep below.
            self._stats_time[report.node_id] = time.monotonic()

    def hot_ps(self, cpu_threshold: float = 80.0) -> List[int]:
        """PS nodes whose reported CPU exceeds the threshold (input to
        the hot-PS auto-scaler; ref local_optimizer.py:66)."""
        with self._lock:
            return sorted(
                node_id for node_id, s in self._stats.items()
                if s.cpu_percent >= cpu_threshold
            )

    def stats(
        self, max_age: Optional[float] = None
    ) -> Dict[int, msg.PsStatsReport]:
        """Latest report per PS; ``max_age`` (seconds) drops stale
        entries so a PS that stopped reporting can't keep steering
        the auto-scaler with its last value."""
        now = time.monotonic()
        with self._lock:
            return {
                node_id: s
                for node_id, s in self._stats.items()
                if max_age is None
                or now - self._stats_time.get(node_id, 0.0) <= max_age
            }
