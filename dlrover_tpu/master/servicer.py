"""Master RPC servicer: binds typed messages to master components.

Parity: dlrover/python/master/servicer.py:62 (MasterServicer.get/report
dispatch), rebuilt on the typed dispatcher of common/comm.py.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Dict, Optional

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcDispatcher
from dlrover_tpu.common.constants import EventAction, RendezvousName
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.job_manager import JobManager
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.rendezvous import (
    ElasticRendezvous,
    NetworkCheckRendezvous,
)
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.task_manager import TaskManager

logger = get_logger("servicer")

_FORENSICS_TOTAL = obs.counter(
    "dlrover_forensics_bundles_total",
    "Forensics bundles reported to the master, by node and kind "
    "(hang / crash / diagnose)",
    ("node", "kind"),
)

_STREAM_BARRIERS = obs.counter(
    "dlrover_stream_barriers_total",
    "Stream barriers committed (coordinated PS flush + durable "
    "ledger record), by dataset",
    ("dataset",),
)
_STREAM_BARRIER_SECONDS = obs.histogram(
    "dlrover_stream_barrier_seconds",
    "Wall time of one stream barrier: PS fleet flush + urgent "
    "journal write",
)
_STREAM_WATERMARK = obs.gauge(
    "dlrover_stream_watermark_records",
    "Contiguously-applied record count at the last stream barrier, "
    "by dataset",
    ("dataset",),
)

# Bounded per-node queues: how many pushed-but-undelivered actions a
# node may accumulate, and how many diagnostics digests the master
# retains per node.
MAX_PENDING_ACTIONS = 16
DIAGNOSTICS_HISTORY = 8
MAX_STORED_DIGEST = 16384
# How many consumed push_action dedupe keys the servicer remembers: a
# replayed remediation push (RPC retry, engine re-fire after a warm
# restart) carrying a key already consumed is a no-op even after the
# original action was delivered — a replayed RESTART_TRAINING must
# not double-bounce a trainer.
MAX_DEDUPE_KEYS = 512
# Server-side cap on traces returned by one non-id TraceQueryRequest
# (each trace can carry up to max_spans_per_trace spans — an
# unbounded listing would approach the gRPC message cap).
MAX_TRACE_QUERY = 64


class MasterServicer:
    def __init__(
        self,
        job_manager: JobManager,
        task_manager: TaskManager,
        elastic_rdzv: ElasticRendezvous,
        check_rdzv: NetworkCheckRendezvous,
        kv_store: Optional[KVStoreService] = None,
        speed_monitor: Optional[SpeedMonitor] = None,
        ps_manager=None,
        fleet=None,
        health=None,
    ):
        self.job_manager = job_manager
        self.task_manager = task_manager
        self.rdzv_managers = {
            RendezvousName.TRAINING: elastic_rdzv,
            RendezvousName.NETWORK_CHECK: check_rdzv,
        }
        self.kv_store = kv_store or KVStoreService()
        self.speed_monitor = speed_monitor or SpeedMonitor()
        # PS-elastic sparse path (ref master/node/ps.py); created
        # lazily so dense-only jobs pay nothing.
        if ps_manager is None:
            from dlrover_tpu.master.ps_manager import PsManager

            ps_manager = PsManager()
        self.ps_manager = ps_manager
        # Fleet telemetry: merges per-host metric snapshots into the
        # master registry (host labels + cross-host aggregates). The
        # JobMaster passes its (render-attached) aggregator and closes
        # it on stop; a bare servicer (tests, embedded use) has no
        # stop hook, so its default stays DETACHED from the global
        # registry's render — snapshots still feed the speed monitor
        # and straggler verdicts.
        if fleet is None:
            from dlrover_tpu.obs.fleet import FleetAggregator

            fleet = FleetAggregator(
                speed_monitor=self.speed_monitor, attach=False
            )
        self.fleet = fleet
        # Health plane: the detector engine whose verdict history the
        # HealthQueryRequest RPC serves. None on a bare servicer
        # (tests, embedded use) — queries then answer "healthy, no
        # verdicts" rather than failing.
        self.health = health
        # Actions queued for agents: a bounded per-node FIFO drained
        # one action per heartbeat. (A plain node_id -> action dict
        # silently dropped the first action when a second was pushed
        # before the next heartbeat — e.g. a restart_training
        # overwritten by a diagnose.)
        self._actions_lock = threading.Lock()
        self._pending_actions: Dict[int, Deque[str]] = {}
        # Consumed push dedupe keys (bounded FIFO of remembered keys):
        # idempotence for remediation pushes per (action, node) —
        # same contract PROFILE/DIAGNOSE get from the in-queue dedupe,
        # extended past delivery.
        self._dedupe_keys: Deque[str] = collections.deque(
            maxlen=MAX_DEDUPE_KEYS
        )
        self._dedupe_key_set: set = set()
        # Remediation engine (set by the JobMaster); None on a bare
        # servicer — queries then answer "disabled, no decisions".
        self.remediation = None
        # Serving router (set by the JobMaster); None on a bare
        # servicer — serve RPCs then answer "serving disabled".
        self.serving = None
        # Trace store (set by the JobMaster); None on a bare servicer
        # — trace queries then answer "tracing disabled".
        self.traces = None
        # Warm-restart journal (set by the JobMaster when state_dir is
        # configured); None on a bare servicer — stream barriers then
        # still flush the PS fleet but answer durable=False.
        self.state_journal = None
        # Stall correlator (set by the JobMaster); None on a bare
        # servicer — stall queries then answer "plane disabled".
        self.stall = None
        # Per-node forensics history (DiagnosticsReport digests),
        # bounded so a crash-looping node cannot grow master memory.
        # Locked: report and query arrive on different RPC worker
        # threads (iterating a deque while another thread appends
        # raises RuntimeError).
        self._diagnostics_lock = threading.Lock()
        self._diagnostics: Dict[int, Deque[msg.DiagnosticsReport]] = {}
        # auto-tuner output pulled by agents (ref: master-pushed
        # ParallelConfig, elastic_agent/config/paral_config_tuner.py)
        self.parallel_config = msg.ParallelConfig()

    def _rdzv(self, name: str):
        mgr = self.rdzv_managers.get(name or RendezvousName.TRAINING)
        if mgr is None:
            raise KeyError(f"unknown rendezvous {name!r}")
        return mgr

    # -- registration -------------------------------------------------------

    def register(self, dispatcher: RpcDispatcher) -> None:
        g = dispatcher.register_get
        r = dispatcher.register_report

        g(msg.JoinRendezvousRequest, self._join_rendezvous)
        g(msg.CommWorldRequest, self._get_comm_world)
        g(msg.WaitingNodeNumRequest, self._num_nodes_waiting)
        g(msg.NetworkCheckQueryRequest, self._query_network_check)
        g(msg.KVStoreGetRequest, self._kv_get)
        g(msg.KVStoreAddRequest, self._kv_add)
        g(msg.TaskRequest, self._get_task)
        g(msg.ShardCheckpointRequest, self._get_shard_checkpoint)
        g(msg.JobNodesRequest, self._get_job_nodes)
        g(msg.ParallelConfigRequest, self._get_parallel_config)
        g(msg.MetricsRequest, self._get_metrics)
        g(msg.DiagnosticsQueryRequest, self._query_diagnostics)
        g(msg.HealthQueryRequest, self._query_health)
        g(msg.StallQueryRequest, self._query_stall)
        g(msg.RemediationQueryRequest, self._query_remediation)
        g(msg.TraceQueryRequest, self._query_traces)
        g(msg.ServeSubmitRequest, self._serve_submit)
        g(msg.ServeResultRequest, self._serve_result)
        g(msg.ServePullRequest, self._serve_pull)
        g(msg.ServeQueryRequest, self._serve_query)
        r(msg.ServeCompletedReport, self._serve_complete)
        r(msg.ServeStatsReport, self._serve_stats)

        r(msg.KVStoreSetRequest, self._kv_set)
        r(msg.DatasetShardParams, self._create_dataset)
        r(msg.TaskResultRequest, self._report_task_result)
        r(msg.NetworkCheckResultRequest, self._report_network_result)
        r(msg.StepReport, self._report_step)
        r(msg.ResourceStats, self._report_resource)
        r(msg.MetricsSnapshotReport, self._report_metrics_snapshot)
        r(msg.DiagnosticsReport, self._report_diagnostics)
        r(msg.ProfileActionRequest, self._profile_node_req)
        r(msg.NodeFailureReport, self._report_failure)
        r(msg.NodeSucceededReport, self._report_succeeded)
        r(msg.HeartbeatRequest, self._heartbeat)
        r(msg.NodeAddressRequest, self._register_node)
        r(msg.RestoreShardRequest, self._restore_shards)

        g(msg.PartitionMapRequest, self._get_partition_map)
        r(msg.PsRegisterRequest, self._register_ps)
        r(msg.PsStatsReport, self._report_ps_stats)

        g(msg.StreamBarrierRequest, self._stream_barrier)
        g(msg.StreamBarrierQueryRequest, self._query_stream_barrier)

    def _noop(self, req):
        return None

    # -- rendezvous ---------------------------------------------------------

    def _join_rendezvous(self, req: msg.JoinRendezvousRequest):
        if self._cordoned_now(req.node_id):
            # The benched agent raced its CORDON delivery into a
            # rejoin (mirror of the restart_training TOCTOU in
            # _heartbeat): admitting it would form a world around a
            # host about to park its trainer mid-collective. Refuse
            # the join and re-assert the cordon on its next heartbeat.
            self.push_action(req.node_id, EventAction.CORDON.value)
            return msg.JoinRendezvousResponse(round=-1)
        mgr = self._rdzv(req.rdzv_name)
        round_ = mgr.join(req.node_rank, req.local_world_size)
        return msg.JoinRendezvousResponse(round=round_)

    def _get_comm_world(self, req: msg.CommWorldRequest):
        mgr = self._rdzv(req.rdzv_name)
        rank = req.node_rank if req.node_rank >= 0 else req.node_id
        round_, group, world = mgr.get_comm_world(rank)
        return msg.CommWorldResponse(
            rdzv_name=req.rdzv_name, round=round_, group=group, world=world
        )

    def _num_nodes_waiting(self, req: msg.WaitingNodeNumRequest):
        mgr = self._rdzv(req.rdzv_name)
        return msg.WaitingNodeNumResponse(waiting_num=mgr.num_nodes_waiting())

    def _report_network_result(self, req: msg.NetworkCheckResultRequest):
        mgr = self.rdzv_managers[RendezvousName.NETWORK_CHECK]
        mgr.report_result(req.node_id, req.normal, req.elapsed_time)
        return None

    def _query_network_check(self, req: msg.NetworkCheckQueryRequest):
        mgr = self.rdzv_managers[RendezvousName.NETWORK_CHECK]
        if req.kind == "straggler":
            nodes, reason = mgr.get_stragglers()
            # The check-time verdict only sees the pre-training
            # benchmark; the speed monitor scores live step times, so
            # a node that slowed down mid-run still surfaces here.
            # The check rendezvous speaks RANKS while the speed
            # monitor is keyed by node id — translate before the
            # union, or a relaunched node's id could flag whichever
            # healthy agent happens to hold that rank.
            slow = []
            for nid in self.speed_monitor.stragglers():
                node = self.job_manager.get_node(nid)
                slow.append(
                    node.rank
                    if node is not None and node.rank >= 0
                    else nid
                )
            if slow:
                nodes = sorted(set(nodes) | set(slow))
                if reason == "waiting":
                    reason = ""
        else:
            nodes, reason = mgr.check_fault_nodes()
        return msg.NetworkCheckQueryResponse(nodes=nodes, reason=reason)

    # -- kv store -----------------------------------------------------------

    def _kv_get(self, req: msg.KVStoreGetRequest):
        found = self.kv_store.has(req.key)
        return msg.KVStoreGetResponse(
            found=found, value=self.kv_store.get(req.key)
        )

    def _kv_set(self, req: msg.KVStoreSetRequest):
        self.kv_store.set(req.key, req.value)
        return None

    def _kv_add(self, req: msg.KVStoreAddRequest):
        return msg.KVStoreAddResponse(
            value=self.kv_store.add(req.key, req.amount)
        )

    # -- data sharding ------------------------------------------------------

    def _create_dataset(self, req: msg.DatasetShardParams):
        shard_size = req.batch_size * req.num_minibatches_per_shard
        self.task_manager.create_dataset(
            dataset_name=req.dataset_name,
            dataset_size=req.dataset_size,
            shard_size=max(shard_size, 1),
            num_epochs=req.num_epochs,
            shuffle=req.shuffle,
            storage_type=req.storage_type or "table",
            task_type=req.task_type or "training",
            num_stream_partitions=max(req.num_stream_partitions, 1),
        )
        return None

    def _get_task(self, req: msg.TaskRequest):
        task = self.task_manager.get_task(req.node_id, req.dataset_name)
        shard = None
        if task.shard is not None:
            shard = msg.Shard(
                name=task.shard.name,
                start=task.shard.start,
                end=task.shard.end,
                record_indices=task.shard.record_indices or [],
                partition=task.shard.partition,
            )
        return msg.Task(
            task_id=task.task_id, task_type=task.task_type, shard=shard
        )

    # -- stream barriers ----------------------------------------------------

    def _stream_barrier(self, req: msg.StreamBarrierRequest):
        """One barrier = one atomic cut across all three planes: the
        trainer has quiesced its applies before calling; here the
        ledger frontier is read, the PS fleet delta-flushes stamped
        with (epoch, HWM), and the barrier record lands in the warm-
        restart journal with an urgent synchronous flush. Only after
        the journal write returns is the barrier acknowledged durable
        — a master or PS death at any point either replays to the
        previous cut or to this one, never between."""
        t0 = time.monotonic()
        with obs.span(
            "stream.barrier",
            dataset=req.dataset_name,
            epoch=req.epoch,
            step=req.step,
        ):
            frontier = self.task_manager.ledger_watermarks(
                req.dataset_name
            )
            hwm = {
                str(p): int(w)
                for p, w in frontier["watermarks"].items()
            }
            flushed = self.ps_manager.flush_all(
                req.step, epoch=req.epoch, hwm=hwm
            )
            flush_gen = 0
            durable = False
            if self.state_journal is not None:
                record = self.task_manager.record_barrier(
                    req.dataset_name, req.epoch, req.step,
                    flushed_rows=flushed,
                )
                path = self.state_journal.flush()
                if path:
                    durable = True
                    # master_state-<seq>.json: seq is the generation.
                    try:
                        flush_gen = int(
                            path.rsplit("-", 1)[1].split(".")[0]
                        )
                    except (IndexError, ValueError):
                        flush_gen = 0
                    record["flush_gen"] = flush_gen
                    self.task_manager.record_barrier(
                        req.dataset_name, req.epoch, req.step,
                        flush_gen=flush_gen, flushed_rows=flushed,
                    )
            else:
                self.task_manager.record_barrier(
                    req.dataset_name, req.epoch, req.step,
                    flushed_rows=flushed,
                )
        _STREAM_BARRIERS.inc(dataset=req.dataset_name)
        _STREAM_BARRIER_SECONDS.observe(time.monotonic() - t0)
        _STREAM_WATERMARK.set(
            frontier["records"], dataset=req.dataset_name
        )
        return msg.StreamBarrierResponse(
            dataset_name=req.dataset_name,
            epoch=req.epoch,
            step=req.step,
            offsets={
                int(p): int(o) for p, o in frontier["offsets"].items()
            },
            watermarks={
                int(p): int(w)
                for p, w in frontier["watermarks"].items()
            },
            flush_gen=flush_gen,
            flushed_rows=flushed,
            durable=durable,
        )

    def _query_stream_barrier(self, req: msg.StreamBarrierQueryRequest):
        """Last durable barrier cut (what a restarted trainer resumes
        from)."""
        rec = self.task_manager.last_barrier(req.dataset_name)
        if rec is None:
            return msg.StreamBarrierResponse(
                dataset_name=req.dataset_name
            )
        return msg.StreamBarrierResponse(
            dataset_name=req.dataset_name,
            epoch=int(rec.get("epoch", -1)),
            step=int(rec.get("step", 0)),
            offsets={
                int(p): int(o)
                for p, o in rec.get("offsets", {}).items()
            },
            watermarks={
                int(p): int(w)
                for p, w in rec.get("watermarks", {}).items()
            },
            flush_gen=int(rec.get("flush_gen", 0)),
            flushed_rows=int(rec.get("flushed_rows", 0)),
            durable=bool(rec.get("flush_gen", 0)),
        )

    def _report_task_result(self, req: msg.TaskResultRequest):
        # node_id makes the report idempotent against replays: after
        # an agent reconnect, a retried result for a shard the master
        # already re-queued to another node must not act.
        self.task_manager.report_task_result(
            req.dataset_name, req.task_id, req.success,
            node_id=req.node_id,
        )
        return None

    def _get_shard_checkpoint(self, req: msg.ShardCheckpointRequest):
        content = self.task_manager.get_shard_checkpoint(req.dataset_name)
        return msg.ShardCheckpointResponse(content=content)

    def _restore_shards(self, req: msg.RestoreShardRequest):
        self.task_manager.restore_shard_checkpoint(
            req.dataset_name, req.content
        )
        return None

    # -- monitoring ---------------------------------------------------------

    def _report_step(self, req: msg.StepReport):
        ts = req.timestamp or time.time()
        self.speed_monitor.collect_global_step(req.step, ts, req.tokens)
        if req.node_id >= 0:
            self.speed_monitor.collect_node_step(
                req.node_id, req.step, timestamp=ts
            )
        # Mirror the step into the goodput stream: this is how
        # productive time (and recovery closure) is accounted even
        # when host-side tracing is off and snapshots carry no events.
        if self.fleet.goodput is not None:
            self.fleet.goodput.add_events(
                [{"name": "trainer.step", "ts": ts,
                  "step": req.step, "node_id": req.node_id}]
            )
        return None

    def _report_metrics_snapshot(self, req: msg.MetricsSnapshotReport):
        self.fleet.ingest(req)
        return None

    def _report_resource(self, req: msg.ResourceStats):
        node = self.job_manager.get_node(req.node_id)
        if node is not None:
            node.config_resource.used_cpu = req.cpu_percent
            node.config_resource.used_memory_mb = req.memory_mb
            node.config_resource.hbm_used_gb = req.hbm_used_gb
            node.config_resource.duty_cycle = req.duty_cycle
        return None

    def _report_failure(self, req: msg.NodeFailureReport):
        node = self.job_manager.get_node(req.node_id)
        rank = node.rank if node is not None else req.node_id
        if req.diagnostics:
            # Attached forensics digest: surfaced in the master log +
            # trace (the bounded history is fed by the agent's
            # companion DiagnosticsReport), kept OUT of the exit
            # classifier's error_data.
            obs.event(
                "node.failure_diagnostics",
                node_id=req.node_id,
                size=len(req.diagnostics),
            )
            logger.info(
                "failure diagnostics from node %d:\n%s",
                req.node_id,
                req.diagnostics[:MAX_STORED_DIGEST],
            )
        action = self.job_manager.handle_failure_report(
            req.node_id,
            req.error_data,
            req.level,
            req.restart_count,
            fatal=req.fatal,
        )
        self.task_manager.recover_node_tasks(req.node_id)
        self.speed_monitor.remove_running_node(req.node_id)
        for mgr in self.rdzv_managers.values():
            mgr.remove_alive_node(req.node_id, node_rank=rank)
        # The failure opens a recovery interval in the goodput
        # accounting; the matching trainer.first_step_done arrives in
        # a later agent snapshot's event payload.
        if self.fleet.goodput is not None:
            self.fleet.goodput.add_events(
                [{
                    "name": "node.fail",
                    "ts": time.time(),
                    "node_id": req.node_id,
                }]
            )
        return msg.NodeFailureResponse(action=action)

    def _report_succeeded(self, req: msg.NodeSucceededReport):
        self.job_manager.handle_node_succeeded(req.node_id)
        self.speed_monitor.remove_running_node(req.node_id)
        return None

    def _heartbeat(self, req: msg.HeartbeatRequest):
        self.job_manager.update_heartbeat(req.node_id)
        action = EventAction.NONE.value
        with self._actions_lock:
            queue = self._pending_actions.get(req.node_id)
            while queue:
                action = queue.popleft()
                if (
                    action == EventAction.RESTART_TRAINING.value
                    and self._cordoned_now(req.node_id)
                ):
                    # A restart that RACED the cordon (the peer
                    # broadcast snapshots the worker list before the
                    # remediation thread flips the flag): the agent
                    # overloads RESTART_TRAINING as un-cordon, so
                    # delivering it would silently put the benched
                    # host back into the world. Re-checking at
                    # delivery time closes the TOCTOU; the rollback
                    # path clears the flag BEFORE pushing its un-park
                    # restart, so a legitimate un-cordon is never
                    # dropped here.
                    logger.warning(
                        "dropping stale restart_training for "
                        "cordoned node %d", req.node_id,
                    )
                    action = EventAction.NONE.value
                    continue
                break
            if queue is not None and not queue:
                self._pending_actions.pop(req.node_id, None)
        return msg.HeartbeatResponse(action=action)

    def _cordoned_now(self, node_id: int) -> bool:
        node = self.job_manager.get_node(node_id)
        return node is not None and getattr(node, "cordoned", False)

    def push_action(
        self, node_id: int, action: str, dedupe_key: Optional[str] = None
    ) -> bool:
        """Queue an action for the node's next heartbeats (FIFO, one
        per heartbeat). Control actions are idempotent, so an action
        already queued is not queued again (two node deaths in one
        monitor tick mean ONE restart_training per survivor, exactly
        as the old last-write-wins dict behaved — without it being
        able to silently swallow a DIFFERENT action). Bounded: when a
        node stops heartbeating, the oldest action is dropped (with a
        warning) rather than growing the queue forever.

        ``dedupe_key``: an idempotency token for pushes that may be
        REPLAYED (remediation decisions, retried operator RPCs). The
        first push consumes the key; any later push carrying the same
        key is a no-op even after the original action was delivered,
        so a replayed restart_training cannot double-bounce a trainer
        the way the in-queue dedupe alone could not prevent. Returns
        True when the action was actually enqueued."""
        with self._actions_lock:
            if dedupe_key is not None:
                if dedupe_key in self._dedupe_key_set:
                    return False
                if len(self._dedupe_keys) >= MAX_DEDUPE_KEYS:
                    self._dedupe_key_set.discard(self._dedupe_keys[0])
                self._dedupe_keys.append(dedupe_key)
                self._dedupe_key_set.add(dedupe_key)
            queue = self._pending_actions.setdefault(
                node_id, collections.deque()
            )
            if action in queue:
                return False
            if len(queue) >= MAX_PENDING_ACTIONS:
                dropped = queue.popleft()
                logger.warning(
                    "node %d action queue full (%d); dropping oldest "
                    "action %r to enqueue %r",
                    node_id, MAX_PENDING_ACTIONS, dropped, action,
                )
            queue.append(action)
            return True

    def restart_peers(
        self,
        exclude_id: int,
        dedupe_prefix: Optional[str] = None,
    ) -> None:
        """Push RESTART_TRAINING to every alive training peer of a
        departed/benched node so survivors re-rendezvous instead of
        blocking on collectives with it. The ONE broadcast loop —
        master node-death handling and the remediation engine both
        route here. Cordoned peers are deliberately skipped: their
        agents overload RESTART_TRAINING as the un-cordon signal, so
        a broadcast reaching one would silently put the benched host
        back into the world."""
        for peer in self.job_manager.alive_workers(include_chief=True):
            if peer.id != exclude_id:
                self.push_action(
                    peer.id,
                    EventAction.RESTART_TRAINING.value,
                    dedupe_key=(
                        f"{dedupe_prefix}:peer{peer.id}"
                        if dedupe_prefix
                        else None
                    ),
                )

    def pending_actions(self, node_id: int) -> list:
        """Undelivered actions for a node (observability/tests)."""
        with self._actions_lock:
            return list(self._pending_actions.get(node_id, ()))

    # -- forensics / diagnostics -------------------------------------------

    def _report_diagnostics(self, req: msg.DiagnosticsReport):
        record = msg.DiagnosticsReport(
            node_id=req.node_id,
            kind=req.kind or "unknown",
            bundle_path=req.bundle_path,
            digest=(req.digest or "")[:MAX_STORED_DIGEST],
            timestamp=req.timestamp or time.time(),
        )
        with self._diagnostics_lock:
            history = self._diagnostics.setdefault(
                req.node_id,
                collections.deque(maxlen=DIAGNOSTICS_HISTORY),
            )
            history.append(record)
        _FORENSICS_TOTAL.inc(node=str(req.node_id), kind=record.kind)
        obs.event(
            "node.diagnostics",
            node_id=req.node_id,
            kind=record.kind,
            bundle_path=record.bundle_path,
        )
        logger.info(
            "forensics from node %d (%s): bundle=%s digest=%d bytes",
            req.node_id, record.kind, record.bundle_path or "-",
            len(record.digest),
        )
        return None

    def _query_diagnostics(self, req: msg.DiagnosticsQueryRequest):
        with self._diagnostics_lock:
            if req.node_id >= 0:
                reports = list(
                    self._diagnostics.get(req.node_id, ())
                )
            else:
                reports = [
                    r
                    for node_id in sorted(self._diagnostics)
                    for r in self._diagnostics[node_id]
                ]
        return msg.DiagnosticsQueryResponse(reports=reports)

    @staticmethod
    def _verdict_msg(v) -> msg.HealthVerdictMsg:
        d = v.to_dict()
        return msg.HealthVerdictMsg(
            detector=d["detector"],
            severity=d["severity"],
            message=d["message"],
            node_id=d["node_id"],
            host=d["host"],
            suggested_action=d["suggested_action"],
            evidence_series=d["evidence_series"],
            evidence=d["evidence"],
            metrics=d["metrics"],
            timestamp=d["timestamp"],
            resolved=d["resolved"],
        )

    def _query_health(self, req: msg.HealthQueryRequest):
        """The health plane's typed read channel: current score +
        active verdicts (optionally the transition history), filtered
        to one node when asked."""
        if self.health is None:
            return msg.HealthQueryResponse(score=1.0)

        def keep(v) -> bool:
            return req.node_id < 0 or v.node_id == req.node_id

        verdicts = [
            self._verdict_msg(v)
            for v in self.health.active_verdicts()
            if keep(v)
        ]
        history = []
        if req.include_history:
            history = [
                self._verdict_msg(v)
                for v in self.health.history()
                if keep(v)
            ]
        return msg.HealthQueryResponse(
            score=self.health.health_score(),
            verdicts=verdicts,
            history=history,
        )

    def _query_remediation(self, req: msg.RemediationQueryRequest):
        """The remediation engine's typed read channel: enabled/dry-
        run mode, cordoned nodes, the decision history with governor
        audit trails, and whether a probation window is currently
        failing."""
        if self.remediation is None:
            return msg.RemediationQueryResponse(enabled=False)
        return self.remediation.query_response(
            node_id=req.node_id, limit=req.limit
        )

    def _query_stall(self, req: msg.StallQueryRequest):
        """The stall-localization plane's typed read channel: the
        correlator's per-host progress table and incident state —
        ``obs_report --stall``'s feed."""
        if self.stall is None:
            return msg.StallQueryResponse(enabled=False)
        return msg.StallQueryResponse(
            enabled=True, snapshot=self.stall.snapshot()
        )

    def recent_diagnostics(self, node_id: int) -> list:
        """One node's forensics history (DiagnosticsReport records,
        newest last) — the stall correlator cross-links coordinated
        capture bundles into its incident snapshot through this."""
        with self._diagnostics_lock:
            return list(self._diagnostics.get(node_id, ()))

    def _query_traces(self, req: msg.TraceQueryRequest):
        """The trace store's typed read channel: assembled causal
        timelines by trace id or subject. Non-id queries are capped
        server-side (MAX_TRACE_QUERY newest): an unbounded "give me
        everything" against a full store would serialize ~130k spans
        into one response and blow the gRPC message cap."""
        if self.traces is None:
            return msg.TraceQueryResponse(enabled=False)
        limit = req.limit
        if not req.trace_id:
            limit = (
                min(limit, MAX_TRACE_QUERY)
                if limit > 0
                else MAX_TRACE_QUERY
            )
        return msg.TraceQueryResponse(
            enabled=True,
            traces=self.traces.query(
                trace_id=req.trace_id,
                subject=req.subject,
                limit=limit,
            ),
        )

    # -- serving plane ------------------------------------------------------

    def _serve_submit(self, req: msg.ServeSubmitRequest):
        if self.serving is None:
            return msg.ServeSubmitResponse(
                request_id="", accepted=False
            )
        rid = self.serving.submit(
            prompt=req.prompt,
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature,
            request_id=req.request_id,
        )
        return msg.ServeSubmitResponse(
            request_id=rid or "",
            accepted=rid is not None,
            trace_id=self.serving.trace_of(rid) if rid else "",
        )

    def _serve_result(self, req: msg.ServeResultRequest):
        if self.serving is None:
            return msg.ServeResultResponse(
                request_id=req.request_id
            )
        rec = self.serving.result(req.request_id)
        if rec is None:
            return msg.ServeResultResponse(
                request_id=req.request_id
            )
        return msg.ServeResultResponse(**rec)

    def _serve_pull(self, req: msg.ServePullRequest):
        if self.serving is None:
            return msg.ServePullResponse()
        items = self.serving.pull(
            req.replica_id, max_items=max(req.max_items, 1)
        )
        return msg.ServePullResponse(
            items=[
                msg.ServeWorkItem(
                    request_id=r.request_id,
                    prompt=list(r.prompt),
                    max_new_tokens=r.max_new_tokens,
                    temperature=r.temperature,
                    trace=dict(r.trace),
                    handoff=dict(r.handoff or {}),
                )
                for r in items
            ]
        )

    def _serve_complete(self, req: msg.ServeCompletedReport):
        if self.serving is None:
            return None
        self.serving.complete(
            replica_id=req.replica_id,
            request_id=req.request_id,
            tokens=req.tokens,
            ttft_s=req.ttft_s,
            tpot_s=req.tpot_s,
            finish_reason=req.finish_reason,
            error=req.error,
            phases=req.phases,
            handoff=dict(req.handoff) if req.handoff else None,
        )
        return None

    def _serve_stats(self, req: msg.ServeStatsReport):
        if self.serving is not None:
            self.serving.report_stats(req.replica_id, req.stats)
        return None

    def _serve_query(self, req: msg.ServeQueryRequest):
        if self.serving is None:
            return msg.ServeQueryResponse(enabled=False)
        return msg.ServeQueryResponse(
            enabled=True, snapshot=self.serving.snapshot()
        )

    def diagnose_node(self, node_id: int) -> None:
        """Queue an on-demand stack-and-state snapshot on the node
        (operator trigger or the SpeedMonitor's straggler/hang
        verdict); delivered via its next heartbeat."""
        self.push_action(node_id, EventAction.DIAGNOSE.value)

    def profile_node(self, node_id: int) -> None:
        """Queue an on-demand N-step performance capture on the node
        (operator RPC or the SpeedMonitor's straggler verdict): its
        agent asks the co-hosted trainer for a step-phase + MFU
        digest, shipped back as DiagnosticsReport(kind="profile")."""
        self.push_action(node_id, EventAction.PROFILE.value)

    def _profile_node_req(self, req: msg.ProfileActionRequest):
        self.profile_node(req.node_id)
        obs.event("node.profile_requested", node_id=req.node_id)
        return None

    def _register_node(self, req: msg.NodeAddressRequest):
        node = self.job_manager.register_node(
            node_type=req.node_type or "worker",
            node_id=req.node_id if req.node_id >= 0 else None,
            addr=req.node_ip,
            labels=dict(req.labels or {}),
        )
        # Evaluators and data workers live outside the training
        # world: they must not enter the rendezvous alive-sets (their
        # check times would pollute the worker straggler median) nor
        # the speed monitor's step accounting.
        from dlrover_tpu.common.constants import NodeType

        if getattr(node, "cordoned", False):
            # A restarted agent on a benched host knows nothing of
            # its cordon (the flag lived in the old agent's memory):
            # re-assert it — park the fresh trainer, keep the node
            # out of the rendezvous alive-sets and speed accounting —
            # until the remediation engine un-cordons or retires it.
            self.push_action(node.id, EventAction.CORDON.value)
            return None
        if node.type == NodeType.REPLICA:
            # Serving replicas live in the node table (heartbeats,
            # watchdog, remediation) but outside the TRAINING world:
            # no rendezvous membership, no step accounting. Their
            # registration feeds the router's replica registry —
            # role-typed (prefill/decode/mixed) for the two-stage
            # dispatch; a PENDING launch's label stands in when the
            # process itself declared none.
            if self.serving is not None:
                role = (req.labels or {}).get(
                    "serving_role"
                ) or node.labels.get("serving_role") or "mixed"
                self.serving.register_replica(
                    node.id, addr=req.node_ip, role=role
                )
            return None
        if node.type not in (
            NodeType.EVALUATOR, NodeType.DATA_WORKER
        ):
            self.speed_monitor.add_running_node(node.id)
            for mgr in self.rdzv_managers.values():
                mgr.add_alive_node(node.id)
        return None

    def _get_job_nodes(self, req: msg.JobNodesRequest):
        nodes = [
            msg.NodeMeta(
                node_type=n.type,
                node_id=n.id,
                rank=n.rank,
                status=n.status,
                addr=n.host_addr,
                chips=n.config_resource.chips,
            )
            for n in self.job_manager.list_nodes(req.node_type)
        ]
        return msg.JobNodesResponse(nodes=nodes)

    def _get_parallel_config(self, req: msg.ParallelConfigRequest):
        return self.parallel_config

    def _get_metrics(self, req: msg.MetricsRequest):
        from dlrover_tpu import obs

        return msg.MetricsResponse(text=obs.get_registry().render())

    def set_parallel_config(self, config: msg.ParallelConfig) -> None:
        """Called by the auto-tuner; version bump tells agents to
        apply it at the next restart."""
        config.version = self.parallel_config.version + 1
        self.parallel_config = config

    # -- PS-elastic sparse path --------------------------------------------

    def _get_partition_map(self, req: msg.PartitionMapRequest):
        return self.ps_manager.to_msg()

    def _register_ps(self, req: msg.PsRegisterRequest):
        self.ps_manager.register_ps(req.node_id, req.addr)
        # PS hosts are job nodes too: the node table is what the
        # PS auto-scaler plans over (ref master/node/ps.py keeps PS
        # in the same node dict as workers). PS ids are namespaced
        # (constants.ps_node_id) so ps 0 never merges onto worker 0.
        from dlrover_tpu.common.constants import NodeType, ps_node_id

        self.job_manager.register_node(
            node_type=NodeType.EMBEDDING,
            node_id=ps_node_id(req.node_id),
            addr=req.addr,
        )

    def _report_ps_stats(self, req: msg.PsStatsReport):
        from dlrover_tpu.common.constants import ps_node_id

        self.ps_manager.report_stats(req)
        # stats reports double as the PS host's heartbeat — without
        # this the 180s watchdog would kill every healthy PS.
        self.job_manager.update_heartbeat(ps_node_id(req.node_id))

