"""Job master assembly and lifecycle.

Parity: dlrover/python/master/dist_master.py:53 (DistributedJobMaster)
and local_master.py (LocalJobMaster). One ``JobMaster`` serves both
roles: in local/standalone mode it is spawned as a subprocess of the run
CLI on the rank-0 host; on a cluster it runs in its own pod.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dlrover_tpu.common.comm import RpcDispatcher, RpcServer
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.job_manager import JobManager, Scaler
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.metrics import (
    JobMetricCollector,
    LogReporter,
    RegistryReporter,
)
from dlrover_tpu.master.rendezvous import (
    ElasticRendezvous,
    NetworkCheckRendezvous,
)
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.task_manager import TaskManager

logger = get_logger("master")

METRICS_PORT_ENV = "DLROVER_TPU_METRICS_PORT"


class JobMaster:
    def __init__(
        self,
        port: int = 0,
        node_num: int = 1,
        min_nodes: int = 0,
        node_unit: int = 1,
        rdzv_timeout: float = 30.0,
        scaler: Optional[Scaler] = None,
        critical_workers: str = "",
        evaluator_count: int = 0,
        heartbeat_timeout: float = 180.0,
        monitor_interval: float = 30.0,
        job_name: str = "",
        metrics_port: Optional[int] = None,
        collect_interval: float = 60.0,
        state_dir: Optional[str] = None,
        brain=None,
        brain_db: Optional[str] = None,
        health_interval: Optional[float] = None,
        remediation_config: Optional[dict] = None,
        remediation_interval: Optional[float] = None,
        serving_config: Optional[dict] = None,
        job_id: str = "",
        dispatcher=None,
        trace_store=None,
        pool_grant: Optional[int] = None,
    ):
        """``node_num`` is the desired (max) world size; ``min_nodes``
        (default = node_num) is the smallest world the job may proceed
        with after losses — the elastic range of ``--nnodes min:max``.
        ``critical_workers`` ("", "all", "none", "0:3,5:1") marks
        workers whose permanent loss fails the job; ``evaluator_count``
        standalone evaluator nodes are scheduled at prepare().
        ``metrics_port`` (or DLROVER_TPU_METRICS_PORT; 0 = ephemeral)
        serves Prometheus text metrics at GET /metrics.
        ``state_dir`` (or DLROVER_TPU_STATE_DIR) enables master warm
        restart: recoverable state is journaled there as versioned
        JSON snapshots, and prepare() restores from the newest valid
        one so a master reschedule costs seconds, not the job.
        ``brain`` (any object with the BrainService persistence
        surface, e.g. a RemoteBrain) or ``brain_db`` (sqlite path, or
        DLROVER_TPU_BRAIN_DB; default in-memory) is the datastore the
        health plane persists runtime samples, fleet aggregates, and
        verdicts into; ``health_interval`` (or
        DLROVER_TPU_HEALTH_INTERVAL_S, default 15 s) is the detector
        evaluation cadence. ``remediation_config`` /
        ``remediation_interval`` parameterize the self-healing engine
        that acts on critical verdicts (docs/FAULT_TOLERANCE.md
        "Verdict-driven remediation"; DLROVER_TPU_REMEDIATION_* env
        knobs, DLROVER_TPU_REMEDIATION_DRY_RUN=1 to observe without
        acting).

        **Multi-job pool embedding** (docs/MULTI_JOB.md): ``job_id``
        names this master's job inside a pool; ``dispatcher`` (a
        per-job RpcDispatcher the pool's JobRoutingDispatcher routes
        ``_job``-tagged envelopes to) makes this master SHARE the
        pool's RPC server instead of owning one — its node table,
        rendezvous, shard ledger, and kv store stay per-job objects
        behind that routing key. ``trace_store`` shares the pool's
        TraceStore so every job's spans are queryable at the pool
        level; ``pool_grant`` caps this job's scalable node count at
        its pool grant (``JobManager.pool_grant``). All four default
        to the unchanged single-job behavior."""
        self.node_num = node_num
        self.job_id = job_id
        self.evaluator_count = evaluator_count
        self.job_manager = JobManager(
            scaler=scaler,
            critical_workers=critical_workers,
            heartbeat_timeout=heartbeat_timeout,
            monitor_interval=monitor_interval,
        )
        self.job_manager.pool_grant = pool_grant
        # Inside a pool, the job id is the natural job name default.
        job_name = job_name or job_id
        self.task_manager = TaskManager()
        self.speed_monitor = SpeedMonitor()
        self.kv_store = KVStoreService()
        from dlrover_tpu.master.ps_manager import PsManager

        self.ps_manager = PsManager()
        # Fleet telemetry: goodput accountant + per-host snapshot
        # aggregator, rendered into the same registry the /metrics
        # endpoint and MetricsRequest RPC serve — and, new, recorded
        # as bounded HISTORY in the time-series store the health
        # detectors query windows over.
        from dlrover_tpu.obs.fleet import FleetAggregator
        from dlrover_tpu.obs.goodput import GoodputAccountant
        from dlrover_tpu.obs.timeseries import TimeSeriesStore
        from dlrover_tpu.obs.trace_store import TraceStore

        self.timeseries = TimeSeriesStore()
        self.goodput = GoodputAccountant(timeseries=self.timeseries)
        # Distributed-trace assembly (bounded, ring-retained like the
        # request ledger): in-master planes feed it directly; trace-
        # tagged events in agent snapshots arrive via the fleet
        # aggregator. Read via TraceQueryRequest / obs_report --trace.
        # A pool-embedded master shares the pool's store, so pool
        # lifecycle spans and this job's rendezvous/serving spans
        # assemble into the same queryable timelines.
        self.traces = (
            trace_store if trace_store is not None else TraceStore()
        )
        self.fleet = FleetAggregator(
            speed_monitor=self.speed_monitor,
            goodput=self.goodput,
            timeseries=self.timeseries,
            trace_store=self.traces,
        )
        self.speed_monitor.timeseries = self.timeseries
        self.elastic_rdzv = ElasticRendezvous()
        self.check_rdzv = NetworkCheckRendezvous()
        for rdzv in (self.elastic_rdzv, self.check_rdzv):
            rdzv.update_params(
                min_nodes=min_nodes if min_nodes > 0 else node_num,
                max_nodes=node_num,
                waiting_timeout=rdzv_timeout,
                node_unit=node_unit,
            )
        self.servicer = MasterServicer(
            job_manager=self.job_manager,
            task_manager=self.task_manager,
            elastic_rdzv=self.elastic_rdzv,
            check_rdzv=self.check_rdzv,
            kv_store=self.kv_store,
            speed_monitor=self.speed_monitor,
            ps_manager=self.ps_manager,
            fleet=self.fleet,
        )
        # Serving plane: the traffic router replicas pull work from.
        # Always constructed (stdlib-only, idle until a replica
        # registers); ``serving_config`` tunes SLOs/watchdogs, env
        # DLROVER_TPU_SERVE_* otherwise (docs/SERVING.md).
        from dlrover_tpu.serving.router import ServingRouter

        self.serving = ServingRouter(
            job_manager=self.job_manager,
            config=serving_config,
            job_name=(
                job_name
                or os.getenv("DLROVER_TPU_JOB_NAME", "default")
            ),
            trace_sink=self.traces,
        )
        self.servicer.serving = self.serving
        self.servicer.traces = self.traces
        # Rendezvous rounds are traces too: each round's start ->
        # complete interval lands in the store as one rdzv.round span.
        self.elastic_rdzv.trace_sink = self.traces
        self.check_rdzv.trace_sink = self.traces
        # Brain datastore: where the health plane persists runtime
        # samples, fleet aggregates + goodput ratio, and verdicts —
        # the same channel ROADMAP item 2's policy engine reads. An
        # injected `brain` (e.g. brain.server.RemoteBrain for a
        # standalone deployment) wins; else a local sqlite store
        # (DLROVER_TPU_BRAIN_DB path, default in-memory).
        if brain is None:
            from dlrover_tpu.brain.service import BrainService

            if brain_db is None:
                brain_db = (
                    os.getenv("DLROVER_TPU_BRAIN_DB", "") or ":memory:"
                )
            brain = BrainService(brain_db)
        self.brain = brain
        # Health plane: detector engine over the time-series history,
        # queueing PROFILE/DIAGNOSE on critical verdicts through the
        # servicer's per-node action FIFO.
        from dlrover_tpu.obs.health import HealthMonitor

        self.health = HealthMonitor(
            store=self.timeseries,
            speed_monitor=self.speed_monitor,
            job_manager=self.job_manager,
            fleet=self.fleet,
            goodput=self.goodput,
            action_sink=self.servicer.push_action,
            serving=self.serving,
            brain=self.brain,
            job_name=(
                job_name
                or os.getenv("DLROVER_TPU_JOB_NAME", "default")
            ),
            heartbeat_timeout=heartbeat_timeout,
            interval=health_interval,
        )
        self.servicer.health = self.health
        # Stall-localization plane: correlates the fleet's shipped
        # progress beacons on the health tick, localizes collective
        # stalls to one host, mints stall.incident traces, and queues
        # the coordinated all-host DIAGNOSE+PROFILE capture through
        # the same per-node action FIFO.
        from dlrover_tpu.obs.stall import StallCorrelator

        self.stall = StallCorrelator(
            fleet=self.fleet,
            traces=self.traces,
            capture=self.servicer.push_action,
            diagnostics=self.servicer.recent_diagnostics,
        )
        self.health.attach_stall(self.stall)
        self.servicer.stall = self.stall
        # Remediation engine: acts on the health plane's critical
        # verdicts through the master's own seams (cordon-then-replace
        # via ScalePlan, restart_training via the heartbeat FIFO,
        # elastic shrink at the next rendezvous boundary), governed by
        # hysteresis / blast-radius / shared cooldowns / probation.
        from dlrover_tpu.master.remediation import RemediationEngine

        self.remediation = RemediationEngine(
            health=self.health,
            job_manager=self.job_manager,
            servicer=self.servicer,
            fleet=self.fleet,
            store=self.timeseries,
            traces=self.traces,
            speed_monitor=self.speed_monitor,
            rdzv_managers=(self.elastic_rdzv, self.check_rdzv),
            serving=self.serving,
            brain=self.brain,
            min_nodes=min_nodes if min_nodes > 0 else node_num,
            job_name=(
                job_name
                or os.getenv("DLROVER_TPU_JOB_NAME", "default")
            ),
            config=remediation_config,
            interval=remediation_interval,
        )
        self.servicer.remediation = self.remediation
        # A freshly-scored straggler gets a fleet `diagnose` AND a
        # `profile`: its agent SIGUSR1s the training process for a
        # stack digest and asks the trainer for an N-step phase/MFU
        # capture while the host is still slow — verdicts become
        # diagnosable AND measurable, not just flagged.
        self.speed_monitor.on_straggler = self._on_straggler
        # PS-strategy auto-scaling starts on demand (sparse/CTR jobs):
        # master.start_ps_autoscaler() wires the hot-PS optimizer to
        # the registered PS fleet.
        self.ps_auto_scaler = None
        # Job-fact aggregation (runtime, node counts, speed, failures)
        # periodically logged AND mirrored into the obs registry the
        # Prometheus endpoint serves.
        self.metric_collector = JobMetricCollector(
            job_name or os.getenv("DLROVER_TPU_JOB_NAME", "default"),
            self.job_manager,
            self.speed_monitor,
            reporters=[LogReporter(), RegistryReporter()],
            interval=collect_interval,
        )
        if metrics_port is None:
            port_s = os.getenv(METRICS_PORT_ENV, "")
            metrics_port = int(port_s) if port_s else None
        self._metrics_port = metrics_port
        self.metrics_server = None
        if dispatcher is None:
            dispatcher = RpcDispatcher()
            self.servicer.register(dispatcher)
            self._server = RpcServer(dispatcher, port=port)
        else:
            # Pool embedding: register into the provided per-job
            # dispatcher; the pool's shared RpcServer owns the port
            # and routes `_job`-tagged envelopes here.
            self.servicer.register(dispatcher)
            self._server = None
        self._stopped = threading.Event()
        self._warm_restarted = False
        # Warm-restart journal: recoverable master state -> versioned
        # JSON snapshots under state_dir, written (debounced) on
        # state-changing events plus a low-frequency timer.
        from dlrover_tpu.master.state_store import (
            STATE_DIR_ENV,
            MasterStateStore,
            StateJournal,
        )

        if state_dir is None:
            state_dir = os.getenv(STATE_DIR_ENV, "") or None
        self.state_dir = state_dir
        self.state_journal: Optional[StateJournal] = None
        if state_dir:
            self.state_journal = StateJournal(
                MasterStateStore(state_dir), self._collect_state
            )
            mark = self.state_journal.mark_dirty
            self.job_manager.add_listener(mark)
            self.task_manager.on_state_change = mark
            self.kv_store.on_change = mark
            self.elastic_rdzv.on_state_change = mark
            self.check_rdzv.on_state_change = mark
            # Verdict transitions and remediation decisions are
            # recoverable state too: without journaling them, a warm
            # restart re-fires a sticky verdict's action immediately
            # (the cooldown stamp died with the process) and forgets
            # in-flight cordons/probations.
            self.health.on_state_change = mark
            self.remediation.on_state_change = mark
            # The PS partition map survives a master bounce: the PS
            # fleet keeps serving it, so the restored master must
            # adopt rather than re-derive it (ps_manager snapshot).
            self.ps_manager.on_state_change = mark
            # Stream barriers flush the journal synchronously and
            # report the generation back to the trainer.
            self.servicer.state_journal = self.state_journal
        # Nodes can die without their agent ever reporting (pod
        # deleted, preemption, heartbeat timeout). The servicer's
        # failure-report path does this cleanup inline; DELETED events
        # from handle_node_gone / the watchdog must trigger the same
        # shard requeue + rendezvous removal (all idempotent).
        self.job_manager.add_listener(self._on_node_event)

    def _on_node_event(self, node, event_type: str) -> None:
        from dlrover_tpu.common.constants import NodeEventType

        if event_type != NodeEventType.DELETED:
            return
        if node.type == NodeType.REPLICA:
            # A dead serving replica: its in-flight requests requeue
            # to the survivors (a kill costs latency, not requests).
            # Replicas never held shards, rendezvous membership, or
            # step accounting, so the training cleanup below does not
            # apply — and must not bounce the training fleet.
            self.serving.replica_gone(node.id)
            return
        self.task_manager.recover_node_tasks(node.id)
        self.speed_monitor.remove_running_node(node.id)
        # Departed node: its metric snapshot must leave the fleet view
        # now, not after the TTL; its loss is badput until the fleet
        # steps again.
        self.fleet.remove_node(node.id)
        self.goodput.add_events(
            [{"name": "node.gone", "ts": time.time(), "node_id": node.id}]
        )
        # Only training-world roles ever entered the rendezvous (the
        # register path skips evaluators and data workers, and PS
        # hosts register via their own RPC): removing one here would
        # evict the WORKER with the same rank from the waiting set —
        # and a dead DATA_WORKER must never restart the training
        # fleet; its only cleanup is the shard requeue above.
        if node.type not in (
            NodeType.EVALUATOR,
            NodeType.EMBEDDING,
            NodeType.DATA_WORKER,
        ):
            for rdzv in (self.elastic_rdzv, self.check_rdzv):
                rdzv.remove_alive_node(node.id, node_rank=node.rank)
            # A cordoned node already LEFT the training world when the
            # remediation engine benched it: retiring its pod now (the
            # cordon-then-replace finalization) must not bounce the
            # healthy fleet a second time.
            if getattr(node, "cordoned", False):
                return
            # Survivors must not block on collectives with the dead
            # peer until some long transport timeout: push a restart
            # so their next heartbeat sends them back to rendezvous,
            # which completes with the shrunken world (>= min_nodes).
            # (ref: torch elastic restarts the worker group on
            # membership change, elastic_agent/torch/training.py:564.)
            self.servicer.restart_peers(node.id)
        if node.type == NodeType.EMBEDDING:
            # A dead PS host (heartbeat timeout / cluster event): move
            # its partitions to the survivors now — clients are already
            # blocking on the stale map.
            from dlrover_tpu.common.constants import node_ps_id

            self.ps_manager.remove_ps(node_ps_id(node.id))

    # -- warm restart --------------------------------------------------------

    def _collect_state(self) -> dict:
        """Everything a replacement master needs to carry the job on:
        node table, rendezvous round/world + waiters, shard ledger,
        kv-store contents (the JAX bootstrap keys), speed progress."""
        return {
            "job_manager": self.job_manager.to_snapshot(),
            "elastic_rdzv": self.elastic_rdzv.to_snapshot(),
            "check_rdzv": self.check_rdzv.to_snapshot(),
            "task_manager": self.task_manager.to_snapshot(),
            "kv_store": self.kv_store.to_snapshot(),
            "speed_monitor": self.speed_monitor.to_snapshot(),
            "health": self.health.to_snapshot(),
            "remediation": self.remediation.to_snapshot(),
            "ps_manager": self.ps_manager.to_snapshot(),
        }

    def _maybe_warm_restart(self) -> bool:
        """Restore from the newest valid snapshot, if any. Called
        from prepare() before any serving thread starts, so restore
        never races live RPCs."""
        if self.state_journal is None:
            return False
        doc = self.state_journal.store.load_latest()
        if doc is None:
            return False
        state = doc["state"]
        try:
            self.job_manager.restore_snapshot(
                state.get("job_manager", {})
            )
            self.elastic_rdzv.restore_snapshot(
                state.get("elastic_rdzv", {})
            )
            self.check_rdzv.restore_snapshot(
                state.get("check_rdzv", {})
            )
            self.task_manager.restore_snapshot(
                state.get("task_manager", {})
            )
            self.kv_store.restore_snapshot(state.get("kv_store", {}))
            self.speed_monitor.restore_snapshot(
                state.get("speed_monitor", {})
            )
            self.health.restore_snapshot(state.get("health", {}))
            self.remediation.restore_snapshot(
                state.get("remediation", {})
            )
            self.ps_manager.restore_snapshot(
                state.get("ps_manager", {})
            )
        except Exception:  # noqa: BLE001 — a corrupt-but-parseable
            # snapshot must degrade to a cold start, not a crash loop
            logger.exception(
                "warm restart from %s failed; starting cold",
                doc.get("path"),
            )
            # All-or-nothing: components restored before the failure
            # must not survive into the "cold" start — a node table
            # without its kv bootstrap keys (or rendezvous round
            # without its ledger) is a state agents can't reason
            # about. Empty snapshots reset each component.
            self.job_manager.restore_snapshot({})
            self.elastic_rdzv.restore_snapshot({})
            self.check_rdzv.restore_snapshot({})
            self.task_manager.reset()
            self.kv_store.restore_snapshot({})
            self.speed_monitor.restore_snapshot({})
            self.health.restore_snapshot({})
            self.remediation.restore_snapshot({})
            self.ps_manager.restore_snapshot({})
            return False
        age_s = max(time.time() - float(doc.get("saved_at", 0.0)), 0.0)
        alive = len(self.job_manager.alive_nodes())
        datasets = len(state.get("task_manager", {}).get("datasets", {}))
        logger.warning(
            "master WARM RESTART from %s (snapshot age %.1fs): "
            "%d alive nodes, %d datasets, rendezvous round %d",
            doc.get("path"), age_s, alive, datasets,
            self.elastic_rdzv.round,
        )
        import dlrover_tpu.obs as obs

        # The recovery-timeline anchor for master-death drills: the
        # outage's downtime is (this event's ts - kill time), and the
        # goodput accountant books the gap as recovery via the same
        # stream.
        obs.event(
            "master.warm_restart",
            snapshot_age_s=round(age_s, 3),
            snapshot_path=str(doc.get("path")),
            alive_nodes=alive,
            datasets=datasets,
            rdzv_round=self.elastic_rdzv.round,
        )
        self.goodput.add_events(
            [{"name": "master.warm_restart", "ts": time.time()}]
        )
        return True

    @property
    def warm_restarted(self) -> bool:
        return self._warm_restarted

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError(
                "pool-embedded master has no own server; use the "
                "pool master's port"
            )
        return self._server.port

    @property
    def addr(self) -> str:
        if self._server is None:
            raise RuntimeError(
                "pool-embedded master has no own server; use the "
                "pool master's addr"
            )
        return self._server.addr

    def _on_straggler(self, node_id: int) -> None:
        """Fresh straggler verdict: snapshot its stacks (diagnose)
        and measure where its step time goes (profile)."""
        self.servicer.diagnose_node(node_id)
        self.servicer.profile_node(node_id)

    def prepare(self) -> None:
        # Restore BEFORE the server accepts its first RPC: agents
        # must never observe a half-restored ledger.
        self._warm_restarted = self._maybe_warm_restart()
        if self._server is not None:
            self._server.start()
        self.job_manager.start()
        self.task_manager.start()
        self.metric_collector.start()
        if self.state_journal is not None:
            self.state_journal.start()
        self.health.start()
        self.remediation.start()
        # Serving autoscale/SLO loop: no-ops until the serving plane
        # has ever seen a replica or request.
        self.serving.start()
        if self._metrics_port is not None:
            from dlrover_tpu.obs.exposition import MetricsHTTPServer

            self.metrics_server = MetricsHTTPServer(
                port=self._metrics_port,
                health=self.health.healthz_payload,
            )
            self.metrics_server.start()
        # Any job may register PS hosts (sparse path); their liveness
        # probing must not depend on --ps_autoscale. A dead PS is
        # failed over in ~10 s — well inside the sparse client's
        # stale-map retry budget — vs the 180 s node-heartbeat timeout.
        # No-op while no PS is registered. Drills shrink detection
        # latency via the env knobs (stream_soak runs whole kill
        # cycles in seconds).
        self.ps_manager.start_liveness_monitor(
            interval=float(
                os.getenv("DLROVER_TPU_PS_LIVENESS_INTERVAL", "2.0")
            ),
            ping_timeout=float(
                os.getenv("DLROVER_TPU_PS_LIVENESS_TIMEOUT", "3.0")
            ),
        )
        if self.evaluator_count > 0:
            self.job_manager.ensure_role(
                NodeType.EVALUATOR, self.evaluator_count
            )

    def start_ps_autoscaler(self, interval: float = 30.0) -> None:
        """Enable PS-strategy auto-scaling (hot-PS migration + worker
        adjustment) for sparse/CTR jobs. Parity:
        dlrover/python/master/node/job_auto_scaler.py:136
        start_auto_scaling."""
        if self.ps_auto_scaler is None:
            from dlrover_tpu.master.auto_scaler import (
                PsTrainingAutoScaler,
            )

            self.ps_auto_scaler = PsTrainingAutoScaler(
                self.job_manager,
                self.speed_monitor,
                self.ps_manager,
                interval=interval,
            )
            self.ps_auto_scaler.start()

    def run(self, poll_interval: float = 2.0) -> int:
        """Block until the job completes; returns an exit code."""
        try:
            while not self._stopped.wait(poll_interval):
                if self.job_manager.job_failed():
                    reason, detail = self.job_manager.job_failure
                    logger.error(
                        "job failed (%s): %s; master exiting",
                        reason,
                        detail,
                    )
                    # Reclaim the rest of the fleet — without this the
                    # surviving pods keep training against a dead
                    # master until they individually time out.
                    self.job_manager.terminate_job()
                    return 1
                if self.job_manager.all_workers_done():
                    logger.info("all workers finished; master exiting")
                    # Evaluators follow the training fleet: retire any
                    # still-alive ones instead of leaving them orphaned.
                    self.job_manager.retire_role(NodeType.EVALUATOR)
                    return 0
        except KeyboardInterrupt:
            return 1
        return 0

    def stop(self) -> None:
        self._stopped.set()
        if self.state_journal is not None:
            # Final flush first: a clean stop leaves the freshest
            # possible snapshot for the next incarnation.
            self.state_journal.stop(final_flush=True)
        if self.ps_auto_scaler is not None:
            self.ps_auto_scaler.stop()
        self.ps_manager.stop_liveness_monitor()
        self.serving.stop()
        self.remediation.stop()
        self.health.stop()
        self.task_manager.stop()
        self.job_manager.stop()
        # stop() joins the collector thread: after this returns no
        # late snapshot can race the server teardown below.
        self.metric_collector.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        # Unhook the fleet collector from the (process-global)
        # registry so a stopped master stops contributing lines.
        self.fleet.close()
        if self._server is not None:
            self._server.stop(0)


def run_master(
    port: int = 0,
    node_num: int = 1,
    node_unit: int = 1,
    rdzv_timeout: float = 30.0,
) -> JobMaster:
    master = JobMaster(
        port=port,
        node_num=node_num,
        node_unit=node_unit,
        rdzv_timeout=rdzv_timeout,
    )
    master.prepare()
    return master
