"""Dataset splitters for dynamic data sharding.

Parity: dlrover/python/master/shard/dataset_splitter.py:144,257,359
(TableDatasetSplitter / TextDatasetSplitter / StreamingDatasetSplitter).
A splitter turns a dataset into epoch-aware shards of
``batch_size * num_minibatches_per_shard`` records; the TaskManager
queues them to workers. On TPU the worker side maps shard index ranges
onto per-host `jax.Array` feed batches.
"""

from __future__ import annotations

import dataclasses
import json
import random
from abc import ABC, abstractmethod
from typing import List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("splitter")


@dataclasses.dataclass
class Shard:
    """A contiguous [start, end) range of records of one dataset.

    ``record_indices`` optionally carries a shuffled index list for
    text-style datasets where order must be randomized per epoch.
    """

    name: str
    start: int
    end: int
    record_indices: Optional[List[int]] = None


class DatasetSplitter(ABC):
    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
    ):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = shard_size
        self.num_epochs = num_epochs
        self.epoch = 0

    @abstractmethod
    def create_shards(self) -> None:
        """Populate shards for the next epoch."""

    @abstractmethod
    def get_shards(self) -> List[Shard]:
        ...

    def epoch_finished(self) -> bool:
        return self.epoch >= self.num_epochs

    def to_checkpoint(self) -> dict:
        return {
            "dataset_name": self.dataset_name,
            "dataset_size": self.dataset_size,
            "shard_size": self.shard_size,
            "num_epochs": self.num_epochs,
            "epoch": self.epoch,
        }

    def restore_checkpoint(self, state: dict) -> None:
        self.epoch = state.get("epoch", 0)


class TableDatasetSplitter(DatasetSplitter):
    """Shards a record-addressable table dataset by index ranges."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        max_shard_count: int = 50000,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle
        self.max_shard_count = max_shard_count
        self._shards: List[Shard] = []

    def create_shards(self) -> None:
        # Huge datasets are covered in sub-epoch windows of at most
        # max_shard_count shards: keep a sliding offset and only advance
        # the epoch once the window reaches the end of the data, so no
        # record is ever silently dropped (parity with the reference's
        # _split_epoch_for_huge_dataset).
        offset = getattr(self, "_sub_offset", 0)
        if offset == 0:
            self.epoch += 1
        shards = []
        window_records = self.max_shard_count * self.shard_size
        end_of_window = min(offset + window_records, self.dataset_size)
        for start in range(offset, end_of_window, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(Shard(self.dataset_name, start, end))
        self._sub_offset = 0 if end_of_window >= self.dataset_size else end_of_window
        if self.shuffle:
            random.shuffle(shards)
        self._shards = shards
        logger.info(
            "dataset %s epoch %d: %d shards of %d records "
            "(window [%d, %d))",
            self.dataset_name,
            self.epoch,
            len(shards),
            self.shard_size,
            offset,
            end_of_window,
        )

    def epoch_finished(self) -> bool:
        # Mid-window: the current epoch still has uncovered records.
        if getattr(self, "_sub_offset", 0) > 0:
            return False
        return super().epoch_finished()

    def get_shards(self) -> List[Shard]:
        return self._shards

    def to_checkpoint(self) -> dict:
        state = super().to_checkpoint()
        state["sub_offset"] = getattr(self, "_sub_offset", 0)
        return state

    def restore_checkpoint(self, state: dict) -> None:
        super().restore_checkpoint(state)
        self._sub_offset = state.get("sub_offset", 0)


class TextDatasetSplitter(DatasetSplitter):
    """Shards a line-indexed text dataset, shuffling record indices
    within (and optionally across) shards per epoch."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle
        self._shards: List[Shard] = []

    def create_shards(self) -> None:
        self.epoch += 1
        indices = list(range(self.dataset_size))
        if self.shuffle:
            random.shuffle(indices)
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(
                    self.dataset_name,
                    start,
                    end,
                    record_indices=indices[start:end],
                )
            )
        self._shards = shards

    def get_shards(self) -> List[Shard]:
        return self._shards


class StreamingDatasetSplitter(DatasetSplitter):
    """Shards an unbounded stream by advancing partition offsets.

    ``dataset_size`` < 0 means infinite; shards are fabricated on demand
    from the current offset.
    """

    def __init__(
        self,
        dataset_name: str,
        shard_size: int,
        dataset_size: int = -1,
        num_epochs: int = 1,
        fetch_batch: int = 100,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.offset = 0
        self.fetch_batch = fetch_batch
        self._shards: List[Shard] = []

    def epoch_finished(self) -> bool:
        if self.dataset_size < 0:
            return False
        return self.offset >= self.dataset_size

    def create_shards(self) -> None:
        if self.epoch == 0:
            self.epoch = 1
        shards = []
        for _ in range(self.fetch_batch):
            if 0 <= self.dataset_size <= self.offset:
                break
            end = self.offset + self.shard_size
            if self.dataset_size >= 0:
                end = min(end, self.dataset_size)
            shards.append(Shard(self.dataset_name, self.offset, end))
            self.offset = end
        self._shards = shards

    def get_shards(self) -> List[Shard]:
        return self._shards

    def to_checkpoint(self) -> dict:
        state = super().to_checkpoint()
        state["offset"] = self.offset
        return state

    def restore_checkpoint(self, state: dict) -> None:
        super().restore_checkpoint(state)
        self.offset = state.get("offset", 0)


def new_dataset_splitter(
    storage_type: str,
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
) -> DatasetSplitter:
    if storage_type in ("", "table"):
        return TableDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    if storage_type == "text":
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    if storage_type == "streaming":
        return StreamingDatasetSplitter(
            dataset_name, shard_size, dataset_size, num_epochs
        )
    raise ValueError(f"unknown dataset storage type {storage_type!r}")


def splitter_state_to_json(splitter: DatasetSplitter, extra: dict) -> str:
    state = splitter.to_checkpoint()
    state.update(extra)
    return json.dumps(state)
