"""Dataset splitters for dynamic data sharding.

Parity: dlrover/python/master/shard/dataset_splitter.py:144,257,359
(TableDatasetSplitter / TextDatasetSplitter / StreamingDatasetSplitter).
A splitter turns a dataset into epoch-aware shards of
``batch_size * num_minibatches_per_shard`` records; the TaskManager
queues them to workers. On TPU the worker side maps shard index ranges
onto per-host `jax.Array` feed batches.
"""

from __future__ import annotations

import dataclasses
import json
import random
from abc import ABC, abstractmethod
from typing import Dict, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("splitter")


@dataclasses.dataclass
class Shard:
    """A contiguous [start, end) range of records of one dataset.

    ``record_indices`` optionally carries a shuffled index list for
    text-style datasets where order must be randomized per epoch.
    ``partition`` is the stream partition the shard was fabricated
    from (streaming datasets only; 0 otherwise) — start/end then index
    that partition's own record space, and ``record_indices`` carries
    the striped global record ids.
    """

    name: str
    start: int
    end: int
    record_indices: Optional[List[int]] = None
    partition: int = 0


class DatasetSplitter(ABC):
    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
    ):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = shard_size
        self.num_epochs = num_epochs
        self.epoch = 0

    @abstractmethod
    def create_shards(self) -> None:
        """Populate shards for the next epoch."""

    @abstractmethod
    def get_shards(self) -> List[Shard]:
        ...

    def epoch_finished(self) -> bool:
        return self.epoch >= self.num_epochs

    def to_checkpoint(self) -> dict:
        return {
            "dataset_name": self.dataset_name,
            "dataset_size": self.dataset_size,
            "shard_size": self.shard_size,
            "num_epochs": self.num_epochs,
            "epoch": self.epoch,
        }

    def restore_checkpoint(self, state: dict) -> None:
        self.epoch = state.get("epoch", 0)


class TableDatasetSplitter(DatasetSplitter):
    """Shards a record-addressable table dataset by index ranges."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        max_shard_count: int = 50000,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle
        self.max_shard_count = max_shard_count
        self._shards: List[Shard] = []

    def create_shards(self) -> None:
        # Huge datasets are covered in sub-epoch windows of at most
        # max_shard_count shards: keep a sliding offset and only advance
        # the epoch once the window reaches the end of the data, so no
        # record is ever silently dropped (parity with the reference's
        # _split_epoch_for_huge_dataset).
        offset = getattr(self, "_sub_offset", 0)
        if offset == 0:
            self.epoch += 1
        shards = []
        window_records = self.max_shard_count * self.shard_size
        end_of_window = min(offset + window_records, self.dataset_size)
        for start in range(offset, end_of_window, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(Shard(self.dataset_name, start, end))
        self._sub_offset = 0 if end_of_window >= self.dataset_size else end_of_window
        if self.shuffle:
            random.shuffle(shards)
        self._shards = shards
        logger.info(
            "dataset %s epoch %d: %d shards of %d records "
            "(window [%d, %d))",
            self.dataset_name,
            self.epoch,
            len(shards),
            self.shard_size,
            offset,
            end_of_window,
        )

    def epoch_finished(self) -> bool:
        # Mid-window: the current epoch still has uncovered records.
        if getattr(self, "_sub_offset", 0) > 0:
            return False
        return super().epoch_finished()

    def get_shards(self) -> List[Shard]:
        return self._shards

    def to_checkpoint(self) -> dict:
        state = super().to_checkpoint()
        state["sub_offset"] = getattr(self, "_sub_offset", 0)
        return state

    def restore_checkpoint(self, state: dict) -> None:
        super().restore_checkpoint(state)
        self._sub_offset = state.get("sub_offset", 0)


class TextDatasetSplitter(DatasetSplitter):
    """Shards a line-indexed text dataset, shuffling record indices
    within (and optionally across) shards per epoch."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle
        self._shards: List[Shard] = []

    def create_shards(self) -> None:
        self.epoch += 1
        indices = list(range(self.dataset_size))
        if self.shuffle:
            random.shuffle(indices)
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(
                    self.dataset_name,
                    start,
                    end,
                    record_indices=indices[start:end],
                )
            )
        self._shards = shards

    def get_shards(self) -> List[Shard]:
        return self._shards


class StreamingDatasetSplitter(DatasetSplitter):
    """Shards an unbounded stream by advancing per-partition offsets.

    ``dataset_size`` < 0 means infinite; shards are fabricated on
    demand from the current offsets. The stream is striped across
    ``num_stream_partitions``: partition p owns global record ids
    {p, p+P, p+2P, ...} (TextDatasetSplitter's record_indices idiom),
    so independent sources can be consumed concurrently while every
    global id still belongs to exactly one shard.

    Two cursors per partition survive checkpoints:

    * ``part_offsets[p]`` — fabrication frontier: next record (in the
      partition's own space) no shard has been cut for yet.
    * ``watermarks[p]`` — completion frontier: records below it were
      reported done contiguously. Out-of-order completions park in
      ``_done_ranges`` until the gap closes. The watermark is what a
      stream barrier stamps into PS flushes: everything below it is
      both applied and flushed, so neither a PS restore nor a master
      warm restart can lose or re-deliver it.
    """

    def __init__(
        self,
        dataset_name: str,
        shard_size: int,
        dataset_size: int = -1,
        num_epochs: int = 1,
        fetch_batch: int = 100,
        num_stream_partitions: int = 1,
    ):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.fetch_batch = fetch_batch
        self.num_stream_partitions = max(1, int(num_stream_partitions))
        parts = range(self.num_stream_partitions)
        self.part_offsets: Dict[int, int] = {p: 0 for p in parts}
        self.watermarks: Dict[int, int] = {p: 0 for p in parts}
        self._done_ranges: Dict[int, List[List[int]]] = {
            p: [] for p in parts
        }
        self._shards: List[Shard] = []

    @property
    def offset(self) -> int:
        """Total records fabricated across partitions (legacy view)."""
        return sum(self.part_offsets.values())

    def partition_size(self, partition: int) -> int:
        """Record count of one stripe, -1 if the stream is unbounded."""
        if self.dataset_size < 0:
            return -1
        p, n = partition, self.num_stream_partitions
        return max(0, (self.dataset_size - p + n - 1) // n)

    def epoch_finished(self) -> bool:
        if self.dataset_size < 0:
            return False
        return all(
            self.part_offsets[p] >= self.partition_size(p)
            for p in range(self.num_stream_partitions)
        )

    def _global_ids(self, partition: int, start: int, end: int
                    ) -> List[int]:
        n = self.num_stream_partitions
        return [partition + n * i for i in range(start, end)]

    def create_shards(self) -> None:
        if self.epoch == 0:
            self.epoch = 1
        shards: List[Shard] = []
        parts = list(range(self.num_stream_partitions))
        while len(shards) < self.fetch_batch:
            open_parts = [
                p for p in parts
                if self.partition_size(p) < 0
                or self.part_offsets[p] < self.partition_size(p)
            ]
            if not open_parts:
                break
            # Round-robin the least-advanced partition so stripes
            # drain evenly and no watermark lags just from scheduling.
            p = min(open_parts, key=lambda q: self.part_offsets[q])
            start = self.part_offsets[p]
            end = start + self.shard_size
            if self.partition_size(p) >= 0:
                end = min(end, self.partition_size(p))
            shards.append(Shard(
                self.dataset_name, start, end,
                record_indices=self._global_ids(p, start, end),
                partition=p,
            ))
            self.part_offsets[p] = end
        self._shards = shards

    def get_shards(self) -> List[Shard]:
        return self._shards

    def mark_done(self, partition: int, start: int, end: int) -> None:
        """Record [start, end) of ``partition`` as applied; advance the
        watermark over every contiguously-done range."""
        if end <= start:
            return
        wm = self.watermarks.get(partition, 0)
        if end <= wm:
            return  # duplicate report of an already-passed range
        ranges = self._done_ranges.setdefault(partition, [])
        ranges.append([max(start, wm), end])
        ranges.sort()
        merged: List[List[int]] = []
        for r in ranges:
            if merged and r[0] <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], r[1])
            else:
                merged.append(list(r))
        while merged and merged[0][0] <= wm:
            wm = max(wm, merged.pop(0)[1])
        self.watermarks[partition] = wm
        self._done_ranges[partition] = merged

    def watermark_records(self) -> int:
        """Total contiguously-applied records across partitions."""
        return sum(self.watermarks.values())

    def to_checkpoint(self) -> dict:
        state = super().to_checkpoint()
        state["offset"] = self.offset
        state["num_stream_partitions"] = self.num_stream_partitions
        state["part_offsets"] = {
            str(p): o for p, o in self.part_offsets.items()
        }
        state["watermarks"] = {
            str(p): w for p, w in self.watermarks.items()
        }
        state["done_ranges"] = {
            str(p): [list(r) for r in rs]
            for p, rs in self._done_ranges.items()
        }
        return state

    def restore_checkpoint(self, state: dict) -> None:
        super().restore_checkpoint(state)
        self.num_stream_partitions = max(
            1, int(state.get("num_stream_partitions", 1))
        )
        parts = range(self.num_stream_partitions)
        if "part_offsets" in state:
            self.part_offsets = {
                p: int(state["part_offsets"].get(str(p), 0))
                for p in parts
            }
            self.watermarks = {
                p: int(state.get("watermarks", {}).get(str(p), 0))
                for p in parts
            }
            self._done_ranges = {
                p: [
                    [int(a), int(b)]
                    for a, b in state.get("done_ranges", {}).get(
                        str(p), []
                    )
                ]
                for p in parts
            }
        else:
            # Pre-watermark checkpoint: a single scalar offset.
            self.part_offsets = {p: 0 for p in parts}
            self.part_offsets[0] = int(state.get("offset", 0))
            self.watermarks = {p: 0 for p in parts}
            self._done_ranges = {p: [] for p in parts}


def new_dataset_splitter(
    storage_type: str,
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
    num_stream_partitions: int = 1,
) -> DatasetSplitter:
    if storage_type in ("", "table"):
        return TableDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    if storage_type == "text":
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )
    if storage_type == "streaming":
        return StreamingDatasetSplitter(
            dataset_name, shard_size, dataset_size, num_epochs,
            num_stream_partitions=num_stream_partitions,
        )
    raise ValueError(f"unknown dataset storage type {storage_type!r}")


def splitter_state_to_json(splitter: DatasetSplitter, extra: dict) -> str:
    state = splitter.to_checkpoint()
    state.update(extra)
    return json.dumps(state)
