"""Master-hosted key-value store.

Parity: dlrover/python/master/elastic_training/kv_store_service.py. Used
by agents/trainers as the bootstrap store (the role torch's TCPStore
plays in torchelastic; here it hands out the JAX coordinator address and
synchronizes process-id assignment) and for small cross-host blobs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class KVStoreService:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, bytes] = {}
        self._cond = threading.Condition(self._lock)

    def set(self, key: str, value: bytes) -> None:
        with self._cond:
            self._store[key] = value
            self._cond.notify_all()

    def get(self, key: str) -> bytes:
        with self._lock:
            return self._store.get(key, b"")

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def add(self, key: str, amount: int) -> int:
        """Atomic counter add (value stored as decimal string)."""
        with self._cond:
            current = int(self._store.get(key, b"0") or b"0")
            current += amount
            self._store[key] = str(current).encode()
            self._cond.notify_all()
            return current

    def wait(self, key: str, timeout: float = 60.0) -> bytes:
        deadline = time.time() + timeout
        with self._cond:
            while key not in self._store:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"key {key!r} not set in {timeout}s")
                self._cond.wait(remaining)
            return self._store[key]

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
