"""Master-hosted key-value store.

Parity: dlrover/python/master/elastic_training/kv_store_service.py. Used
by agents/trainers as the bootstrap store (the role torch's TCPStore
plays in torchelastic; here it hands out the JAX coordinator address and
synchronizes process-id assignment) and for small cross-host blobs.
"""

from __future__ import annotations

import base64
import threading
import time
from typing import Callable, Dict, Optional


class KVStoreService:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, bytes] = {}
        self._cond = threading.Condition(self._lock)
        # Fired (outside the lock) after every mutation; the JobMaster
        # points this at the state journal so bootstrap keys survive a
        # master restart.
        self.on_change: Optional[Callable[[], None]] = None

    def _changed(self) -> None:
        cb = self.on_change
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — journaling must not
                # break the bootstrap path it records
                pass

    def set(self, key: str, value: bytes) -> None:
        with self._cond:
            self._store[key] = value
            self._cond.notify_all()
        self._changed()

    def get(self, key: str) -> bytes:
        with self._lock:
            return self._store.get(key, b"")

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def add(self, key: str, amount: int) -> int:
        """Atomic counter add (value stored as decimal string)."""
        with self._cond:
            current = int(self._store.get(key, b"0") or b"0")
            current += amount
            self._store[key] = str(current).encode()
            self._cond.notify_all()
            result = current
        self._changed()
        return result

    def wait(self, key: str, timeout: float = 60.0) -> bytes:
        # Monotonic deadline: an NTP step must neither fire this
        # timeout early nor mask it (same bug class as HangDetector).
        deadline = time.monotonic() + timeout
        with self._cond:
            while key not in self._store:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"key {key!r} not set in {timeout}s")
                self._cond.wait(remaining)
            return self._store[key]

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
        self._changed()

    # -- warm-restart snapshot ----------------------------------------------

    def to_snapshot(self) -> dict:
        """JSON-safe dump (values are arbitrary bytes -> base64)."""
        with self._lock:
            return {
                k: base64.b64encode(v).decode("ascii")
                for k, v in self._store.items()
            }

    def restore_snapshot(self, state: dict) -> None:
        with self._cond:
            self._store = {
                k: base64.b64decode(v) for k, v in state.items()
            }
            self._cond.notify_all()
