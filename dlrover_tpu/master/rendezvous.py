"""Master-side rendezvous managers.

Behavior parity with the reference's rendezvous layer
(dlrover/python/master/elastic_training/rdzv_manager.py:113,272,351):

* ``ElasticRendezvous`` — collects joining hosts, freezes a
  communication world once ``max_nodes`` joined or ``min_nodes`` joined
  and the waiting timeout elapsed, rounded down to a multiple of
  ``node_unit`` (a TPU *pod-slice host group*: worlds must be a whole
  number of slices for the ICI mesh to be rectangular).
* ``NetworkCheckRendezvous`` — two-round pairwise health check: round 0
  pairs neighbors, round 1 re-pairs sorted-by-time so a failing pair is
  disambiguated; stragglers are nodes slower than 2x the median.

On TPU the "world" handed back is used to (re)build the
``jax.distributed`` bootstrap (coordinator + process ids), and the
health-check payload is a small psum/all-gather over ICI rather than a
NCCL allgather.
"""

from __future__ import annotations

import math
import statistics
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import get_logger

logger = get_logger("rendezvous")

_RDZV_ROUNDS = obs.counter(
    "dlrover_rendezvous_rounds_total",
    "Completed rendezvous rounds",
    ("name",),
)
_RDZV_WORLD = obs.gauge(
    "dlrover_rendezvous_world_size",
    "Node count of the most recently frozen world",
    ("name",),
)
_RDZV_SECONDS = obs.histogram(
    "dlrover_rendezvous_seconds",
    "Wall time from first join to world freeze",
    ("name",),
)


class RendezvousParameters:
    def __init__(
        self,
        min_nodes: int = 0,
        max_nodes: int = 0,
        waiting_timeout: float = 30.0,
        node_unit: int = 1,
    ):
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.waiting_timeout = waiting_timeout
        self.node_unit = node_unit


class RendezvousManagerBase:
    """Shared join/freeze logic for both rendezvous flavors."""

    name: str = ""

    def __init__(self):
        self._lock = threading.Lock()
        self._params = RendezvousParameters()
        # node_rank -> local_world_size, nodes waiting for the next round
        self._waiting_nodes: Dict[int, int] = {}
        # frozen world for the current round
        self._rdzv_nodes: Dict[int, int] = {}
        self._latest_rdzv_nodes: List[int] = []
        self._alive_nodes: Set[int] = set()
        self._rdzv_round = 0
        # Monotonic stamps (waiting-timeout / elapsed arithmetic must
        # not move when NTP steps the wall clock); 0.0 = unset.
        self._lastcall_time = 0.0
        self._start_rdzv_time = 0.0
        # Fired outside the lock after membership/world changes; the
        # JobMaster points this at the state journal.
        self.on_state_change = None
        # Distributed tracing: one trace per rendezvous round. The
        # JobMaster points trace_sink at its TraceStore; the round's
        # start -> freeze interval lands there as one rdzv.round span
        # and the round events carry its trace id.
        self.trace_sink = None
        self._round_trace = None  # obs.tracer.TraceContext | None
        self._round_start_wall = 0.0

    def _changed(self) -> None:
        cb = self.on_state_change
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001
                pass

    def update_params(
        self,
        min_nodes: int,
        max_nodes: int,
        waiting_timeout: float = 30.0,
        node_unit: int = 1,
    ) -> None:
        with self._lock:
            if self._params.max_nodes == 0:
                self._params = RendezvousParameters(
                    min_nodes, max_nodes, waiting_timeout, node_unit
                )

    @property
    def round(self) -> int:
        return self._rdzv_round

    def add_alive_node(self, node_id: int) -> None:
        with self._lock:
            self._alive_nodes.add(node_id)

    def remove_alive_node(self, node_id: int, node_rank: int = -1) -> None:
        with self._lock:
            self._alive_nodes.discard(node_id)
            rank = node_rank if node_rank >= 0 else node_id
            self._waiting_nodes.pop(rank, None)

    def join(self, node_rank: int, local_world_size: int) -> int:
        """Add a node to the waiting list; returns the round index."""
        joined = False
        with self._lock:
            if not self._waiting_nodes:
                self._start_rdzv_time = time.monotonic()
                logger.info(
                    "%s: start round %d rendezvous",
                    self.name,
                    self._rdzv_round,
                )
            if self._round_trace is None:
                # Round boundary, which is NOT always an empty
                # waiting set: a freeze that leaves surplus waiters
                # behind seeds the next round non-empty, and that
                # churn round must be traced too.
                from dlrover_tpu.obs import tracer as _trace

                self._round_start_wall = time.time()
                self._round_trace = _trace.new_trace_context()
                obs.event(
                    "rdzv.start",
                    rdzv=self.name, round=self._rdzv_round,
                    trace_id=self._round_trace.trace_id,
                    parent_span_id=self._round_trace.span_id,
                )
            if node_rank not in self._waiting_nodes:
                self._waiting_nodes[node_rank] = local_world_size
                # Only a returning member of the frozen world invalidates
                # it (it restarted, so the old world is dead). A brand-new
                # node must NOT wipe the world other members are still
                # fetching — it waits for the next round, which agents
                # enter once num_nodes_waiting() tells them to restart.
                if node_rank in self._latest_rdzv_nodes:
                    self._rdzv_nodes = {}
                self._lastcall_time = time.monotonic()
                joined = True
            round_ = self._rdzv_round
        if joined:
            self._changed()
        return round_

    def _try_complete(self) -> bool:
        """Freeze the world when enough nodes joined. Caller holds lock."""
        waiting_num = len(self._waiting_nodes)
        completed = False
        if waiting_num >= self._params.max_nodes and waiting_num > 0:
            # Never freeze a world larger than max_nodes.
            waiting_num = self._params.max_nodes
            completed = True
        elif (
            waiting_num > 0
            and time.monotonic() - self._lastcall_time
            >= self._params.waiting_timeout
        ):
            # Round down to whole node_units (slices) FIRST, then check
            # the minimum — a rounded-down world below min_nodes is not
            # a viable job and must keep waiting.
            waiting_num = (
                waiting_num // self._params.node_unit
            ) * self._params.node_unit
            if waiting_num >= self._params.min_nodes and waiting_num > 0:
                completed = True
            else:
                return False
        if completed:
            ranks = sorted(self._waiting_nodes.keys())[:waiting_num]
            self._rdzv_nodes = {r: self._waiting_nodes[r] for r in ranks}
            self._latest_rdzv_nodes = list(self._rdzv_nodes.keys())
            for r in ranks:
                self._waiting_nodes.pop(r, None)
            self._lastcall_time = 0.0
            elapsed = time.monotonic() - self._start_rdzv_time
            logger.info(
                "%s: round %d completed with %d nodes in %.2fs; "
                "left waiting: %s",
                self.name,
                self._rdzv_round,
                len(self._rdzv_nodes),
                elapsed,
                self._waiting_nodes,
            )
            _RDZV_ROUNDS.inc(name=self.name)
            _RDZV_WORLD.set(len(self._rdzv_nodes), name=self.name)
            _RDZV_SECONDS.observe(elapsed, name=self.name)
            trace = self._round_trace
            obs.event(
                "rdzv.complete",
                rdzv=self.name, round=self._rdzv_round,
                world_size=len(self._rdzv_nodes),
                elapsed_s=round(elapsed, 3),
                **(
                    {
                        "trace_id": trace.trace_id,
                        "parent_span_id": trace.span_id,
                    }
                    if trace is not None
                    else {}
                ),
            )
            if trace is not None and self.trace_sink is not None:
                self.trace_sink.add_span(
                    trace.trace_id,
                    "rdzv.round",
                    self._round_start_wall or time.time() - elapsed,
                    dur_s=elapsed,
                    span_id=trace.span_id,
                    subject=f"rdzv:{self.name}",
                    rdzv=self.name,
                    round=self._rdzv_round,
                    world_size=len(self._rdzv_nodes),
                )
            self._round_trace = None
        return completed

    # -- warm-restart snapshot ----------------------------------------------

    def to_snapshot(self) -> dict:
        """JSON-safe recoverable state: round, frozen world, pending
        waiters, alive set. Timer stamps are deliberately NOT included
        (monotonic clocks do not survive a process) — restore restarts
        the waiting timeout from 'now'."""
        with self._lock:
            return {
                "round": self._rdzv_round,
                "waiting_nodes": {
                    str(k): v for k, v in self._waiting_nodes.items()
                },
                "rdzv_nodes": {
                    str(k): v for k, v in self._rdzv_nodes.items()
                },
                "latest_rdzv_nodes": list(self._latest_rdzv_nodes),
                "alive_nodes": sorted(self._alive_nodes),
            }

    def restore_snapshot(self, state: dict) -> None:
        with self._lock:
            self._rdzv_round = int(state.get("round", 0))
            self._waiting_nodes = {
                int(k): int(v)
                for k, v in state.get("waiting_nodes", {}).items()
            }
            self._rdzv_nodes = {
                int(k): int(v)
                for k, v in state.get("rdzv_nodes", {}).items()
            }
            self._latest_rdzv_nodes = [
                int(r) for r in state.get("latest_rdzv_nodes", [])
            ]
            self._alive_nodes = {
                int(n) for n in state.get("alive_nodes", [])
            }
            now = time.monotonic()
            # Fresh clocks: the waiting timeout restarts from the
            # warm restart, not from a dead process's monotonic era.
            self._lastcall_time = now if self._waiting_nodes else 0.0
            self._start_rdzv_time = now

    def num_nodes_waiting(self) -> int:
        """Nonzero return tells agents to restart for re-rendezvous.

        A returning member (restart) triggers immediately; brand-new
        nodes only once a whole node_unit (slice) of them is ready.
        """
        with self._lock:
            for rank in self._waiting_nodes:
                if rank in self._latest_rdzv_nodes:
                    return len(self._waiting_nodes)
            if len(self._waiting_nodes) >= self._params.node_unit:
                return len(self._waiting_nodes)
            return 0


class ElasticRendezvous(RendezvousManagerBase):
    """Rendezvous for the training world."""

    name = RendezvousName.TRAINING

    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, int]]:
        completed = False
        with self._lock:
            if not self._rdzv_nodes:
                if self._try_complete():
                    self._rdzv_round += 1
                    completed = True
            result = self._rdzv_round, 0, dict(self._rdzv_nodes)
        if completed:
            self._changed()
        return result


class NetworkCheckRendezvous(RendezvousManagerBase):
    """Two-round pairwise health-check rendezvous.

    Round even: pair adjacent nodes — each pair runs the check payload
    (psum + matmul benchmark) over ICI/DCN. Round odd: re-pair fastest
    with slowest so a node that failed in a bad pair gets a known-good
    partner; a node failing both rounds is faulty.
    """

    name = RendezvousName.NETWORK_CHECK
    CHECK_ROUNDS = 2

    def __init__(self):
        super().__init__()
        self._node_status: Dict[int, bool] = {}
        self._node_times: Dict[int, float] = {}
        self._reported_nodes: Set[int] = set()
        self._node_groups: List[Dict[int, int]] = []
        self._fault_nodes: Set[int] = set()
        self._straggler_nodes: Set[int] = set()
        self._verdict_done = False

    def join(self, node_rank: int, local_world_size: int) -> int:
        with self._lock:
            self._node_groups.clear()
        return super().join(node_rank, local_world_size)

    def get_comm_world(
        self, node_rank: int
    ) -> Tuple[int, int, Dict[int, int]]:
        with self._lock:
            if not self._node_groups:
                if self._try_complete():
                    self._fault_nodes.clear()
                    self._straggler_nodes.clear()
                    self._verdict_done = False
                    self._node_groups = self._group_nodes(self._rdzv_round)
                    logger.info(
                        "network-check round %d groups: %s",
                        self._rdzv_round,
                        self._node_groups,
                    )
                    if self._rdzv_round % self.CHECK_ROUNDS == 0:
                        self._node_status = {}
                        self._node_times = {}
                    self._reported_nodes = set()
                    self._rdzv_round += 1
            for i, group in enumerate(self._node_groups):
                if node_rank in group:
                    return self._rdzv_round, i, dict(group)
            return self._rdzv_round, 0, dict(self._rdzv_nodes)

    def _group_nodes(self, rdzv_round: int) -> List[Dict[int, int]]:
        phase = rdzv_round % self.CHECK_ROUNDS
        groups: List[Dict[int, int]] = []
        if phase == 0:
            # Adjacent pairs; odd node out merges into the last group.
            group: Dict[int, int] = {}
            for rank, lws in sorted(self._rdzv_nodes.items()):
                group[rank] = lws
                if len(group) == 2:
                    groups.append(group)
                    group = {}
            if group:
                if groups:
                    groups[-1].update(group)
                else:
                    groups.append(group)
        else:
            # Pair fastest with slowest from the previous round.
            ordered = [
                rank
                for rank, _ in sorted(
                    self._node_times.items(), key=lambda kv: kv[1]
                )
                if rank in self._rdzv_nodes
            ]
            # Nodes that never reported go in at the end (suspect).
            for rank in sorted(self._rdzv_nodes):
                if rank not in ordered:
                    ordered.append(rank)
            left, right = 0, len(ordered) - 1
            group = {}
            while right >= left:
                group = {}
                group[ordered[left]] = self._rdzv_nodes[ordered[left]]
                group[ordered[right]] = self._rdzv_nodes[ordered[right]]
                if len(group) == 2:
                    groups.append(group)
                left += 1
                right -= 1
            if len(group) == 1:
                if groups:
                    groups[-1].update(group)
                else:
                    groups.append(group)
        return groups

    def report_result(
        self, node_rank: int, normal: bool, elapsed_time: float
    ) -> None:
        with self._lock:
            self._reported_nodes.add(node_rank)
            # Health is sticky-pass across the paired rounds — one bad
            # round may be the partner's fault, so passing anywhere
            # wins — and a node's representative cost is its best time.
            self._node_status[node_rank] = normal or self._node_status.get(
                node_rank, False
            )
            self._node_times[node_rank] = round(
                min(
                    self._node_times.get(node_rank, math.inf),
                    elapsed_time,
                ),
                3,
            )

    def _round_verdict(self) -> bool:
        """Classify the check round once all reports are in. Returns
        False while reports are outstanding.

        Verdict rules: a node whose sticky status never turned healthy
        is faulty; a node slower than twice the median best-time is a
        straggler; and a fully clean fleet fast-forwards the round
        counter to the next CHECK_ROUNDS boundary, so the next check
        request opens a fresh pair of rounds instead of replaying the
        tail of this one. Evaluated at most once per check round (the
        next ``get_comm_world`` completion re-arms it)."""
        if len(self._reported_nodes) < len(self._rdzv_nodes):
            return False
        if not self._verdict_done:
            self._verdict_done = True
            self._fault_nodes.update(
                rank
                for rank, healthy in self._node_status.items()
                if not healthy
            )
            self._straggler_nodes.update(self._slow_outliers())
            if not (self._fault_nodes or self._straggler_nodes):
                self._rdzv_round += -self._rdzv_round % self.CHECK_ROUNDS
        return True

    def check_fault_nodes(self) -> Tuple[List[int], str]:
        """Return ([fault ranks], reason). reason='waiting' while nodes
        are still reporting."""
        with self._lock:
            if not self._round_verdict():
                return [], "waiting"
            reason = "fault" if self._fault_nodes else ""
            return sorted(self._fault_nodes), reason

    def get_stragglers(self) -> Tuple[List[int], str]:
        with self._lock:
            if not self._round_verdict():
                return [], "waiting"
            return sorted(self._straggler_nodes), ""

    def _slow_outliers(self) -> Dict[int, float]:
        """Nodes whose best check time exceeds twice the fleet median."""
        if not self._node_times:
            return {}
        cutoff = 2 * statistics.median(self._node_times.values())
        return {
            rank: t
            for rank, t in self._node_times.items()
            if t > cutoff
        }
