"""Job metric collection and reporting.

Parity with the reference's stats layer
(dlrover/python/master/stats/job_collector.py JobMetricCollector +
reporter.py pluggable reporter backends): the master aggregates job
facts (runtime, node counts, speed, failures) and periodically hands a
snapshot to a reporter. Backends: log (default) and JSON-lines file;
the seam is where a metrics service / Brain datastore plugs in.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import get_logger

logger = get_logger("metrics")


@dataclasses.dataclass
class JobSnapshot:
    timestamp: float
    job_name: str
    runtime_s: float
    global_step: int
    speed_steps_per_s: float
    token_throughput: float
    workers_alive: int
    workers_pending: int
    workers_failed: int
    total_relaunches: int
    failure_counts: Dict[str, int]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Reporter:
    def report(self, snapshot: JobSnapshot) -> None:
        raise NotImplementedError


class LogReporter(Reporter):
    def report(self, snapshot: JobSnapshot) -> None:
        logger.info("job metrics: %s", json.dumps(snapshot.to_dict()))


class JsonFileReporter(Reporter):
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def report(self, snapshot: JobSnapshot) -> None:
        with self._lock, open(self.path, "a") as f:
            f.write(json.dumps(snapshot.to_dict()) + "\n")


class RegistryReporter(Reporter):
    """Mirrors each snapshot into the obs metrics registry, which the
    master exposes in Prometheus text format (HTTP /metrics and the
    MetricsRequest RPC). Event-driven counters (relaunches, rendezvous
    rounds) are incremented at their source; this reporter owns the
    sampled job-level gauges."""

    def __init__(self, registry=None):
        registry = registry or obs.get_registry()
        self._workers = registry.gauge(
            "dlrover_job_workers",
            "Worker nodes by state",
            ("state",),
        )
        self._relaunch_total = registry.gauge(
            "dlrover_job_worker_relaunches",
            "Cumulative relaunch count across current worker nodes",
        )
        self._step = registry.gauge(
            "dlrover_job_global_step", "Latest reported global step"
        )
        self._speed = registry.gauge(
            "dlrover_job_steps_per_second",
            "Training speed over the speed-monitor window",
        )
        self._tokens = registry.gauge(
            "dlrover_job_tokens_per_second",
            "Token throughput over the speed-monitor window",
        )
        self._runtime = registry.gauge(
            "dlrover_job_runtime_seconds", "Master-observed job runtime"
        )

    def report(self, snapshot: JobSnapshot) -> None:
        self._workers.set(snapshot.workers_alive, state="alive")
        self._workers.set(snapshot.workers_pending, state="pending")
        self._workers.set(snapshot.workers_failed, state="failed")
        self._relaunch_total.set(snapshot.total_relaunches)
        self._step.set(snapshot.global_step)
        self._speed.set(snapshot.speed_steps_per_s)
        self._tokens.set(snapshot.token_throughput)
        self._runtime.set(snapshot.runtime_s)


class JobMetricCollector:
    def __init__(
        self,
        job_name: str,
        job_manager,
        speed_monitor,
        reporters: Optional[List[Reporter]] = None,
        interval: float = 60.0,
    ):
        self.job_name = job_name
        self.job_manager = job_manager
        self.speed_monitor = speed_monitor
        self.reporters = reporters or [LogReporter()]
        self.interval = interval
        # Monotonic: only used for the runtime_s duration below.
        self.start_time = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def snapshot(self) -> JobSnapshot:
        nodes = self.job_manager.list_nodes(NodeType.WORKER)
        failure_counts: Dict[str, int] = {}
        for n in nodes:
            reason = n.exit_reason or n.relaunch_reason
            if reason:
                failure_counts[reason] = (
                    failure_counts.get(reason, 0) + 1
                )
        return JobSnapshot(
            timestamp=time.time(),
            job_name=self.job_name,
            runtime_s=time.monotonic() - self.start_time,
            global_step=self.speed_monitor.global_step,
            speed_steps_per_s=self.speed_monitor.running_speed(),
            token_throughput=self.speed_monitor.token_throughput(),
            workers_alive=sum(
                1 for n in nodes if n.status == NodeStatus.RUNNING
            ),
            workers_pending=sum(
                1 for n in nodes if n.status == NodeStatus.PENDING
            ),
            workers_failed=sum(
                1 for n in nodes if n.status == NodeStatus.FAILED
            ),
            total_relaunches=sum(n.relaunch_count for n in nodes),
            failure_counts=failure_counts,
        )

    def collect_once(self) -> JobSnapshot:
        snap = self.snapshot()
        for r in self.reporters:
            try:
                r.report(snap)
            except Exception:  # noqa: BLE001
                logger.warning("reporter failed", exc_info=True)
        return snap

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="metric-collector", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        """Stop and JOIN the collector thread so shutdown is
        deterministic — the loop wakes from its interval wait
        immediately on the stop event, so the join is prompt."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=10.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.collect_once()
            except Exception:  # noqa: BLE001
                logger.warning("metric collection failed", exc_info=True)
