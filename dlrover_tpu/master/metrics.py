"""Job metric collection and reporting.

Parity with the reference's stats layer
(dlrover/python/master/stats/job_collector.py JobMetricCollector +
reporter.py pluggable reporter backends): the master aggregates job
facts (runtime, node counts, speed, failures) and periodically hands a
snapshot to a reporter. Backends: log (default) and JSON-lines file;
the seam is where a metrics service / Brain datastore plugs in.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import get_logger

logger = get_logger("metrics")


@dataclasses.dataclass
class JobSnapshot:
    timestamp: float
    job_name: str
    runtime_s: float
    global_step: int
    speed_steps_per_s: float
    token_throughput: float
    workers_alive: int
    workers_pending: int
    workers_failed: int
    total_relaunches: int
    failure_counts: Dict[str, int]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Reporter:
    def report(self, snapshot: JobSnapshot) -> None:
        raise NotImplementedError


class LogReporter(Reporter):
    def report(self, snapshot: JobSnapshot) -> None:
        logger.info("job metrics: %s", json.dumps(snapshot.to_dict()))


class JsonFileReporter(Reporter):
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def report(self, snapshot: JobSnapshot) -> None:
        with self._lock, open(self.path, "a") as f:
            f.write(json.dumps(snapshot.to_dict()) + "\n")


class JobMetricCollector:
    def __init__(
        self,
        job_name: str,
        job_manager,
        speed_monitor,
        reporters: Optional[List[Reporter]] = None,
        interval: float = 60.0,
    ):
        self.job_name = job_name
        self.job_manager = job_manager
        self.speed_monitor = speed_monitor
        self.reporters = reporters or [LogReporter()]
        self.interval = interval
        self.start_time = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def snapshot(self) -> JobSnapshot:
        nodes = self.job_manager.list_nodes(NodeType.WORKER)
        failure_counts: Dict[str, int] = {}
        for n in nodes:
            reason = n.exit_reason or n.relaunch_reason
            if reason:
                failure_counts[reason] = (
                    failure_counts.get(reason, 0) + 1
                )
        return JobSnapshot(
            timestamp=time.time(),
            job_name=self.job_name,
            runtime_s=time.time() - self.start_time,
            global_step=self.speed_monitor.global_step,
            speed_steps_per_s=self.speed_monitor.running_speed(),
            token_throughput=self.speed_monitor.token_throughput(),
            workers_alive=sum(
                1 for n in nodes if n.status == NodeStatus.RUNNING
            ),
            workers_pending=sum(
                1 for n in nodes if n.status == NodeStatus.PENDING
            ),
            workers_failed=sum(
                1 for n in nodes if n.status == NodeStatus.FAILED
            ),
            total_relaunches=sum(n.relaunch_count for n in nodes),
            failure_counts=failure_counts,
        )

    def collect_once(self) -> JobSnapshot:
        snap = self.snapshot()
        for r in self.reporters:
            try:
                r.report(snap)
            except Exception:  # noqa: BLE001
                logger.warning("reporter failed", exc_info=True)
        return snap

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="metric-collector", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.collect_once()
            except Exception:  # noqa: BLE001
                logger.warning("metric collection failed", exc_info=True)
