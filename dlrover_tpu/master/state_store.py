"""Durable master state: versioned JSON snapshots + a dirty-debounced
journal, so a replacement master can warm-restart instead of taking
the whole fleet down with it.

The master's recoverable state — node table, rendezvous round/world,
dataset-shard ledger, kv-store contents, speed-monitor progress — is
collected by ``JobMaster._collect_state()`` into one JSON document and
written atomically (tmp + fsync + rename) into ``state_dir`` as
``master_state-<seq>.json``. The newest *valid* snapshot wins on
restore: a torn or unparsable file (master killed mid-write is exactly
the case this exists for) falls back to the previous sequence number,
and ``keep`` generations are retained.

Writes are driven two ways, both through :class:`StateJournal`:

* **state-changing events** — components call ``mark_dirty()`` (via
  the hooks JobMaster installs); the journal thread flushes at most
  once per ``min_interval`` so a shard-dispatch hot loop cannot turn
  the master into an fsync benchmark;
* **a low-frequency timer** — every ``timer_interval`` seconds a
  dirty journal is flushed even if the event volume stayed under the
  debounce, bounding staleness for slow-changing state (heartbeats,
  speed-monitor progress).

Nothing here imports master components: the journal takes a
``collect`` callable, so tests can snapshot arbitrary payloads.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, List, Optional, Tuple

from dlrover_tpu.common.log import get_logger

logger = get_logger("state_store")

STATE_DIR_ENV = "DLROVER_TPU_STATE_DIR"
SNAPSHOT_SECONDS_ENV = "DLROVER_TPU_SNAPSHOT_SECONDS"
SNAPSHOT_MIN_INTERVAL_ENV = "DLROVER_TPU_SNAPSHOT_MIN_INTERVAL"

SCHEMA_VERSION = 1
_FILE_RE = re.compile(r"^master_state-(\d+)\.json$")


class MasterStateStore:
    """Atomic, generation-numbered snapshot files in one directory."""

    def __init__(self, state_dir: str, keep: int = 3):
        self.state_dir = state_dir
        self.keep = max(keep, 1)
        os.makedirs(state_dir, exist_ok=True)

    def _generations(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.state_dir)
        except OSError:
            return []
        for name in names:
            m = _FILE_RE.match(name)
            if m:
                out.append(
                    (int(m.group(1)), os.path.join(self.state_dir, name))
                )
        return sorted(out)

    def save(self, payload: dict) -> str:
        """Write the next generation atomically; prune old ones."""
        gens = self._generations()
        seq = (gens[-1][0] + 1) if gens else 1
        doc = {
            "schema_version": SCHEMA_VERSION,
            "seq": seq,
            "saved_at": time.time(),
            "state": payload,
        }
        path = os.path.join(self.state_dir, f"master_state-{seq}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        for _, old in gens[: max(0, len(gens) + 1 - self.keep)]:
            try:
                os.remove(old)
            except OSError:
                pass
        # Sweep tmp files orphaned by a master killed mid-write
        # (their pids never write again, so nothing else reclaims
        # them and repeated bounces would accumulate garbage).
        try:
            for name in os.listdir(self.state_dir):
                if ".json.tmp." in name and not tmp.endswith(name):
                    try:
                        os.remove(os.path.join(self.state_dir, name))
                    except OSError:
                        pass
        except OSError:
            pass
        return path

    def load_latest(self) -> Optional[dict]:
        """Newest snapshot that parses and matches the schema, or
        None. Falls back across generations: the newest file may be a
        torn write from the master's death."""
        for _, path in reversed(self._generations()):
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                logger.warning(
                    "skipping unreadable master snapshot %s", path
                )
                continue
            if (
                isinstance(doc, dict)
                and doc.get("schema_version") == SCHEMA_VERSION
                and isinstance(doc.get("state"), dict)
            ):
                doc["path"] = path
                return doc
            logger.warning(
                "skipping master snapshot %s with unknown schema %r",
                path, doc.get("schema_version") if isinstance(doc, dict)
                else type(doc).__name__,
            )
        return None


class StateJournal:
    """Debounced writer pumping ``collect()`` into a store."""

    def __init__(
        self,
        store: MasterStateStore,
        collect: Callable[[], dict],
        min_interval: Optional[float] = None,
        timer_interval: Optional[float] = None,
    ):
        if min_interval is None:
            min_interval = float(
                os.getenv(SNAPSHOT_MIN_INTERVAL_ENV, "") or 1.0
            )
        if timer_interval is None:
            timer_interval = float(
                os.getenv(SNAPSHOT_SECONDS_ENV, "") or 30.0
            )
        self.store = store
        self._collect = collect
        self.min_interval = min_interval
        self.timer_interval = timer_interval
        self._dirty = threading.Event()
        self._urgent = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._write_lock = threading.Lock()
        self._last_write = 0.0  # monotonic
        self.writes = 0
        self.write_errors = 0

    def mark_dirty(self, *_args, urgent: bool = False, **_kwargs) -> None:
        """Signal that recoverable state changed. Accepts (and
        ignores) arbitrary args so it can be registered directly as a
        node-event listener / on_state_change callback.

        ``urgent=True`` skips the min_interval debounce for the next
        flush: used for acknowledgements the master must not forget
        (shard completions) — the at-least-once window shrinks from
        the debounce interval to the write latency."""
        if urgent:
            self._urgent.set()
        self._dirty.set()

    def flush(self) -> Optional[str]:
        """Write a snapshot now (used at stop and by tests)."""
        with self._write_lock:
            self._dirty.clear()
            self._urgent.clear()
            try:
                path = self.store.save(self._collect())
            except Exception:  # noqa: BLE001 — a full disk must not
                # take down the live control plane it is backing up
                self.write_errors += 1
                logger.warning("master state snapshot failed",
                               exc_info=True)
                return None
            self._last_write = time.monotonic()
            self.writes += 1
            return path

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="master-state-journal", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            # Wake on dirty or after the timer interval, whichever
            # first; then debounce event bursts to min_interval.
            self._dirty.wait(self.timer_interval)
            if self._stop.is_set():
                return
            if not self._dirty.is_set():
                continue
            since = time.monotonic() - self._last_write
            if not self._urgent.is_set() and since < self.min_interval:
                # Debounce event bursts — but an urgent mark (shard
                # completion ack) breaks the sleep and flushes now.
                self._urgent.wait(self.min_interval - since)
                if self._stop.is_set():
                    return
            self.flush()

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        self._dirty.set()  # unblock the timer wait
        self._urgent.set()  # unblock a debounce sleep
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_flush:
            self.flush()
