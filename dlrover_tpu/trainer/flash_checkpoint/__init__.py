"""Flash Checkpoint: zero-stall checkpointing for TPU training.

TPU-native re-design of the reference's Flash Checkpoint stack
(dlrover/trainer/torch/flash_checkpoint/* + elastic_agent/torch/
ckpt_saver.py): the training process stages sharded ``jax.Array``
state into host shared memory in seconds; the host agent persists shm
to storage asynchronously, on a failure signal, or right before an
elastic restart — so a crashed trainer never loses the last in-memory
checkpoint.
"""

from dlrover_tpu.trainer.flash_checkpoint.checkpointer import (
    Checkpointer,
    StorageType,
)
from dlrover_tpu.trainer.flash_checkpoint.engine import CheckpointEngine

__all__ = ["CheckpointEngine", "Checkpointer", "StorageType"]
